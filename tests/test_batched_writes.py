"""Batched write path (ISSUE 5 tentpole): Database.write_batch processes
a batch as columns — one identity pass with a per-batch memo, vectorized
shard routing, ONE commitlog append, one buffer lock per (shard, window)
group, pre-filtered index inserts — and must be INDISTINGUISHABLE from
the per-entry write_tagged loop: identical buffer reads, byte-identical
commitlog output, identical replay streams, identical index results.
Plus per-entry fault isolation and the deterministic crash-mid-batch
durability case (the seeded chaos sweep lives in test_crash_recovery.py).
"""

from __future__ import annotations

import numpy as np
import pytest

from m3_tpu.storage import commitlog
from m3_tpu.storage.database import Database
from m3_tpu.storage.options import (
    DatabaseOptions,
    IndexOptions,
    NamespaceOptions,
    RetentionOptions,
)
from m3_tpu.utils import faults
from m3_tpu.utils.ident import tags_to_id

HOUR = 3600 * 10**9
SEC = 10**9
START = 1_599_998_400_000_000_000  # 2h-aligned block start


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    faults.disable()
    yield
    faults.disable()


def small_opts(index: bool = True) -> NamespaceOptions:
    return NamespaceOptions(
        retention=RetentionOptions(
            retention_ns=24 * HOUR,
            block_size_ns=2 * HOUR,
            buffer_past_ns=10 * 60 * SEC,
        ),
        index=IndexOptions(enabled=index, block_size_ns=2 * HOUR),
        snapshot_enabled=False,
    )


def make_db(path: str, n_shards: int = 4, owned=None,
            flush_every: int = 1 << 20) -> Database:
    db = Database(path, DatabaseOptions(
        n_shards=n_shards, owned_shards=owned,
        commitlog_flush_every_bytes=flush_every))
    db.create_namespace("default", small_opts())
    db.open(START)
    return db


def entries_mixed(n: int = 400):
    """A realistic batch: repeated identities (memo hits), several shards,
    two block windows, interleaved NEW series registrations."""
    return [
        (b"metric-%02d" % (i % 23), [(b"host", b"h%02d" % (i % 5))],
         START + (i % (4 * 3600)) * SEC, float(i))
        for i in range(n)
    ]


def sid_of(entry) -> bytes:
    metric, tags, _t, _v = entry
    return tags_to_id(metric, [tuple(kv) for kv in tags])


def read_all(db: Database, sid: bytes):
    t, v = db.namespaces["default"].read(sid, START, START + 24 * HOUR)
    return t.tolist(), v.view(np.float64).tolist()


# ---------------------------------------------------------------------------
# batch vs loop parity
# ---------------------------------------------------------------------------


class TestBatchLoopParity:
    def test_reads_and_commitlog_bytes_identical(self, tmp_path):
        """The acceptance bar: same entries through write_batch and the
        per-entry loop leave identical buffer state AND byte-identical
        commitlog files (new-series register records spliced at their
        first occurrence, exactly where write() would emit them)."""
        ents = entries_mixed()
        db_b = make_db(str(tmp_path / "batch"))
        db_l = make_db(str(tmp_path / "loop"))
        results = db_b.write_batch("default", ents)
        assert results == [None] * len(ents)
        for m, tags, t, v in ents:
            db_l.write_tagged("default", m, tags, t, v)

        for sid in sorted({sid_of(e) for e in ents}):
            assert read_all(db_b, sid) == read_all(db_l, sid)

        # per-shard accounting parity: warm/cold splits and write seqs
        ns_b, ns_l = db_b.namespaces["default"], db_l.namespaces["default"]
        for shard_id in ns_b.shards:
            sb, sl = ns_b.shards[shard_id], ns_l.shards[shard_id]
            assert (sb.warm_writes, sb.cold_writes) == \
                (sl.warm_writes, sl.cold_writes)
            assert sb._write_seq == sl._write_seq

        db_b._commitlogs["default"].flush(fsync=True)
        db_l._commitlogs["default"].flush(fsync=True)
        [pb] = commitlog.log_files(db_b.commitlog_dir("default"))
        [pl] = commitlog.log_files(db_l.commitlog_dir("default"))
        assert open(pb, "rb").read() == open(pl, "rb").read()
        db_b.close()
        db_l.close()

    def test_commitlog_replay_roundtrip(self, tmp_path):
        """Batched WAL entries replay into the same datapoints after a
        hard kill — bootstrap sees nothing batch-specific."""
        ents = entries_mixed(200)
        db = make_db(str(tmp_path / "db"))
        assert db.write_batch("default", ents) == [None] * len(ents)
        db._commitlogs["default"].flush(fsync=True)
        expect = {sid: read_all(db, sid) for sid in {sid_of(e) for e in ents}}
        # hard kill: no close() flush niceties
        for log in db._commitlogs.values():
            log._f.close()
        db._commitlogs.clear()

        db2 = make_db(str(tmp_path / "db"))
        for sid, want in expect.items():
            assert read_all(db2, sid) == want
        db2.close()

    def test_index_query_parity_and_tag_wire_shapes(self, tmp_path):
        from m3_tpu.index.query import TermQuery

        ents = entries_mixed(200)
        # JSON-wire shape (lists, not tuples) must memoize + insert the same
        ents += [(b"wire", [[b"dc", b"dc1"]], START + i * SEC, float(i))
                 for i in range(3)]
        db_b = make_db(str(tmp_path / "batch"))
        db_l = make_db(str(tmp_path / "loop"))
        assert db_b.write_batch("default", ents) == [None] * len(ents)
        for m, tags, t, v in ents:
            db_l.write_tagged("default", m, [tuple(kv) for kv in tags], t, v)
        for q in (TermQuery(b"host", b"h01"), TermQuery(b"dc", b"dc1")):
            got_b = db_b.namespaces["default"].query_ids(
                q, START, START + 24 * HOUR)
            got_l = db_l.namespaces["default"].query_ids(
                q, START, START + 24 * HOUR)
            assert sorted(d.series_id for d in got_b) == \
                sorted(d.series_id for d in got_l)
            assert len(got_b) > 0
        db_b.close()
        db_l.close()

    def test_steady_state_skips_mutable_and_reseal(self, tmp_path):
        """The seen-set pre-filter: a second batch of already-indexed
        series must not touch the mutable segment — so the sealed-view
        cache stays valid (no re-seal on the next query)."""
        ents = entries_mixed(100)
        db = make_db(str(tmp_path / "db"))
        db.write_batch("default", ents)
        index = db.namespaces["default"].index
        before = {bs: (blk.mutable.n_docs, [id(s) for s in blk.segments()])
                  for bs, blk in index._blocks.items()}
        # same series, later timestamps within the same index blocks
        again = [(m, tags, t + SEC, v + 1) for m, tags, t, v in ents]
        assert db.write_batch("default", again) == [None] * len(again)
        for bs, blk in index._blocks.items():
            n_docs, seg_ids = before[bs]
            assert blk.mutable.n_docs == n_docs
            assert [id(s) for s in blk.segments()] == seg_ids
        db.close()

    def test_seen_set_survives_compaction(self, tmp_path):
        """After compact() moves docs into sealed segments, re-writing
        those series must not re-insert duplicate docs into the fresh
        mutable segment (the re-seal-per-insert failure mode)."""
        ents = entries_mixed(60)
        db = make_db(str(tmp_path / "db"))
        db.write_batch("default", ents)
        index = db.namespaces["default"].index
        index.compact()
        assert all(blk.mutable.n_docs == 0 for blk in index._blocks.values())
        db.write_batch("default", ents)
        assert all(blk.mutable.n_docs == 0 for blk in index._blocks.values())
        db.close()

    def test_empty_and_single_entry(self, tmp_path):
        db = make_db(str(tmp_path / "db"))
        assert db.write_batch("default", []) == []
        [res] = db.write_batch(
            "default", [(b"one", [(b"k", b"v")], START, 1.5)])
        assert res is None
        t, v = read_all(db, tags_to_id(b"one", [(b"k", b"v")]))
        assert t == [START] and v == [1.5]
        db.close()

    def test_session_write_many_uses_in_process_batch(self, tmp_path):
        """An in-process Database now exposes the conn.write_batch
        surface, so Session.write_many op-batches without HTTP."""
        called = []
        db = make_db(str(tmp_path / "db"), n_shards=4)
        orig = db.write_batch
        db.write_batch = lambda ns, ents: called.append(len(ents)) or \
            orig(ns, ents)

        from m3_tpu.client.session import Session
        from m3_tpu.cluster import placement as pl
        from m3_tpu.cluster.placement import Instance
        from m3_tpu.cluster.topology import ConsistencyLevel, TopologyMap

        p = pl.initial_placement([Instance("n1")], n_shards=4,
                                 replica_factor=1)
        topo = TopologyMap(p)
        sess = Session(topo, {"n1": db},
                       write_consistency=ConsistencyLevel.ONE)
        ents = [(b"s-%d" % i, [(b"k", b"v")], START + i * SEC, float(i))
                for i in range(32)]
        assert sess.write_many("default", ents) == [None] * 32
        assert called == [32]
        db.close()


# ---------------------------------------------------------------------------
# per-entry fault isolation
# ---------------------------------------------------------------------------


class TestFaultIsolation:
    def test_unowned_shard_degrades_entry_not_batch(self, tmp_path):
        db = Database(str(tmp_path / "db"), DatabaseOptions(n_shards=4))
        db.create_namespace("default", small_opts())
        db.open(START)
        ents = entries_mixed(100)
        # drop ownership of the shards two sample series route to
        ns = db.namespaces["default"]
        victim_sids = {sid_of(ents[0]), sid_of(ents[1])}
        victim_shards = {ns.shard_set.lookup(s) for s in victim_sids}
        keep = set(range(4)) - victim_shards
        db.assign_shards(keep, START)
        results = db.write_batch("default", ents)
        for e, r in zip(ents, results):
            routed = ns.shard_set.lookup(sid_of(e))
            if routed in victim_shards:
                assert r is not None and "not owned" in r
            else:
                assert r is None
        assert any(r is not None for r in results)
        assert any(r is None for r in results)
        db.close()

    def test_malformed_entry_degrades_entry_not_batch(self, tmp_path):
        db = make_db(str(tmp_path / "db"))
        good = (b"ok", [(b"k", b"v")], START, 1.0)
        bad_ts = (b"bad", [(b"k", b"v")], "not-a-timestamp", 1.0)
        bad_val = (b"bad2", [(b"k", b"v")], START, "NaNify")
        results = db.write_batch("default", [good, bad_ts, bad_val, good])
        assert results[0] is None and results[3] is None
        assert results[1] is not None and results[2] is not None
        t, _v = read_all(db, tags_to_id(b"ok", [(b"k", b"v")]))
        assert t == [START]
        db.close()

    def test_commitlog_error_degrades_whole_batch_but_not_neighbors(
            self, tmp_path):
        """An injected WAL failure (commitlog.write fires per BATCH now)
        fails every entry of that batch — none were durably logged, none
        may reach the buffers — while earlier and later batches are
        untouched."""
        db = make_db(str(tmp_path / "db"))
        b1 = [(b"a", [(b"k", b"v")], START + i * SEC, float(i))
              for i in range(10)]
        b2 = [(b"b", [(b"k", b"v")], START + i * SEC, float(i))
              for i in range(10)]
        b3 = [(b"c", [(b"k", b"v")], START + i * SEC, float(i))
              for i in range(10)]
        with faults.active("commitlog.write=error:n2"):
            assert db.write_batch("default", b1) == [None] * 10
            res2 = db.write_batch("default", b2)
            assert all(r is not None for r in res2)
            assert db.write_batch("default", b3) == [None] * 10
        assert read_all(db, sid_of(b1[0]))[0]  # batch 1 landed
        assert read_all(db, sid_of(b2[0])) == ([], [])  # batch 2 fully out
        assert read_all(db, sid_of(b3[0]))[0]  # batch 3 landed
        db.close()

    def test_db_write_batch_fault_point_fires_per_batch(self, tmp_path):
        db = make_db(str(tmp_path / "db"))
        ents = entries_mixed(50)
        with faults.active("db.write_batch=error:n1") as plan:
            with pytest.raises(faults.InjectedError):
                db.write_batch("default", ents)
            assert db.write_batch("default", ents) == [None] * len(ents)
            # one hit per BATCH, not per entry — and the schedule is the
            # deterministic record a replay asserts against
            assert plan.hits("db.write_batch") == 2
            assert plan.schedule == [("db.write_batch", 1, "error")]
        db.close()

    def test_unknown_namespace_degrades_entries_not_request(self, tmp_path):
        """A whole-batch storage failure at the node (unknown namespace)
        answers 200 with per-entry errors — a 4xx would feed the client's
        breaker and shed a healthy node over a misconfigured namespace."""
        import json

        from m3_tpu.services.dbnode import NodeAPI

        db = make_db(str(tmp_path / "db"))
        api = NodeAPI(db)
        import base64

        b64 = lambda b: base64.b64encode(b).decode()  # noqa: E731
        status, payload = api.handle("POST", "/write_batch", {}, json.dumps({
            "namespace": "nope",
            "entries": [{"metric_b64": b64(b"m"),
                         "tags_b64": [[b64(b"k"), b64(b"v")]],
                         "timestamp_ns": START, "value": 1.0}] * 3,
        }).encode())
        assert status == 200
        results = json.loads(payload)["results"]
        assert len(results) == 3 and all(r is not None for r in results)
        db.close()

    def test_flush_handler_batches_cluster_facade(self, tmp_path):
        """The aggregator flush handler op-batches against cluster
        facades too (write_tagged_batch), falling back to per-metric
        writes — with per-entry counting — when the batch raises."""
        from m3_tpu.aggregator.engine import (
            AggregatedMetric, storage_flush_handler,
        )
        from m3_tpu.metrics.policy import StoragePolicy

        calls = {"batch": 0, "single": 0}

        class FacadeStub:  # ClusterDatabase shape: no write_batch
            def write_tagged_batch(self, ns, entries):
                calls["batch"] += 1
                if ns == "flaky":
                    raise RuntimeError("below consistency")
                return len(entries)

            def write_tagged(self, ns, name, tags, t_ns, value):
                calls["single"] += 1

        policy = StoragePolicy(10 * SEC, 24 * HOUR)
        mk = lambda i: AggregatedMetric(  # noqa: E731
            series_id=b"s%d" % i, tags=((b"__name__", b"m"), (b"k", b"v")),
            timestamp_ns=START + i * SEC, value=float(i), policy=policy)
        handler = storage_flush_handler(
            FacadeStub(), lambda p: "ok" if True else None)
        assert handler([mk(0), mk(1)]) == 2
        assert calls == {"batch": 1, "single": 0}
        handler = storage_flush_handler(FacadeStub(), lambda p: "flaky")
        assert handler([mk(0), mk(1)]) == 2  # per-metric fallback counted
        assert calls["single"] == 2

    def test_crash_mid_batch_flush_keeps_acked_writes(self, tmp_path):
        """Deterministic crash-mid-batch-flush: a torn chunk written
        while a batch crosses the flush threshold kills the writer; the
        previously ACKED (fsynced) batch must survive salvage replay.
        (The seeded sweep over offsets is chaos-lane —
        test_crash_recovery.py::TestChaosFull.)"""
        db = make_db(str(tmp_path / "db"), flush_every=512)
        acked = [(b"acked", [(b"k", b"v")], START + i * SEC, float(i))
                 for i in range(20)]
        assert db.write_batch("default", acked) == [None] * 20
        db._commitlogs["default"].flush(fsync=True)  # the durability ack
        doomed = [(b"doomed-%03d" % i, [(b"k", b"v")], START + i * SEC,
                   float(i)) for i in range(200)]  # crosses flush_every
        with faults.active("commitlog.flush=torn"):
            with pytest.raises(faults.SimulatedCrash):
                db.write_batch("default", doomed)
        # hard kill + recover
        for log in db._commitlogs.values():
            log._f.close()
        db._commitlogs.clear()
        db2 = make_db(str(tmp_path / "db"))
        t, v = read_all(db2, tags_to_id(b"acked", [(b"k", b"v")]))
        assert t == [START + i * SEC for i in range(20)]
        assert v == [float(i) for i in range(20)]
        db2.close()


# ---------------------------------------------------------------------------
# /read_batch stats envelope + selfscrape batching
# ---------------------------------------------------------------------------


class TestStatsEnvelope:
    def test_node_envelope_and_coordinator_merge(self, tmp_path):
        import base64
        import json

        from m3_tpu.services.dbnode import NodeAPI
        from m3_tpu.utils import querystats

        db = make_db(str(tmp_path / "db"))
        ents = entries_mixed(50)
        db.write_batch("default", ents)
        db.flush_all()  # flushed volumes so the read decodes (rungs/bytes)
        api = NodeAPI(db)
        sids = sorted({sid_of(e) for e in ents})
        b64 = lambda b: base64.b64encode(b).decode()  # noqa: E731
        status, payload = api.handle("POST", "/read_batch", {}, json.dumps({
            "namespace": "default",
            "series_ids": [b64(s) for s in sids],
            "start_ns": START, "end_ns": START + 24 * HOUR,
        }).encode())
        assert status == 200
        doc = json.loads(payload)
        assert set(doc) == {"rows", "stats"}
        assert len(doc["rows"]) == len(sids)
        stats = doc["stats"]
        assert stats["blocks"] > 0 and stats["bytes"] > 0
        assert stats["rungs"]  # some decode rung served the groups

        # coordinator half: the envelope merges onto the active record
        st = querystats.start("probe", "default")
        querystats.merge_storage(stats)
        querystats.finish(st)
        assert st.blocks_read == stats["blocks"]
        assert st.bytes_decoded == stats["bytes"]
        assert st.decode_rungs == stats["rungs"]
        db.close()

    def test_collect_shields_outer_record(self):
        from m3_tpu.utils import querystats

        outer = querystats.start("outer")
        with querystats.collect() as st:
            querystats.record(blocks_read=3, bytes_decoded=10)
        assert (st.blocks_read, st.bytes_decoded) == (3, 10)
        assert (outer.blocks_read, outer.bytes_decoded) == (0, 0)
        assert querystats.current() is outer
        querystats.finish(outer)


class TestSelfscrapeBatch:
    def test_scrape_once_is_one_batch(self, tmp_path):
        from m3_tpu.utils import selfscrape
        from m3_tpu.utils.instrument import MetricsRegistry

        reg = MetricsRegistry()
        scope = reg.root_scope("t")
        scope.counter("hits", 5)
        scope.observe("lat_seconds", 0.25)
        db = make_db(str(tmp_path / "db"))
        selfscrape.ensure_namespace(db)
        calls = []
        orig = db.write_batch
        db.write_batch = lambda ns, ents: calls.append((ns, len(ents))) or \
            orig(ns, ents)
        n = selfscrape.scrape_once(db, reg, now_ns=START)
        assert n > 0
        assert len(calls) == 1 and calls[0] == (selfscrape.SELF_NAMESPACE, n)
        # the samples are queryable in the self namespace
        t, v = db.namespaces[selfscrape.SELF_NAMESPACE].read(
            tags_to_id(b"t_hits", []), START, START + HOUR)
        assert t.tolist() == [START] and v.view(np.float64).tolist() == [5.0]
        db.close()
