"""The production traffic rig (tools/rig.py).

Tier-1 (fast) half: seeded-traffic determinism, replayable chaos
schedules, the /metrics histogram parser, and an IN-PROCESS rig smoke
run — real CoordinatorAPI + Database + admission controller, zero
subprocesses — proving the ledger/shed/isolation machinery end to end.

Chaos half (`run_tests.sh rig`, marked `chaos` -> never tier-1): real
spawned processes — 2 dbnodes (RF=2) + a 3-replica quorum kvd metadata
plane + coordinator + aggregator — under a seeded kill/partition
schedule with live load: zero acked-write loss, stitched-warning reads
during the outage, runtime quota push through kvd, and the noisy-tenant
isolation SLO from the server-side per-tenant histograms."""

from __future__ import annotations

import json
import os
import time

import pytest

from m3_tpu.tools import rig as rigmod
from m3_tpu.tools.rig import (
    ChaosSchedule,
    Rig,
    RigConfig,
    TrafficGen,
    WriteLedger,
)


# ---------------------------------------------------------------------------
# determinism (tier-1)


class TestTrafficDeterminism:
    def test_same_seed_same_sequence(self):
        cfg = RigConfig(seed=11, tenants=("a", "b", "c"))
        g1, g2 = TrafficGen(cfg), TrafficGen(cfg)
        for _ in range(50):
            assert g1.next_batch(0) == g2.next_batch(0)
            assert g1.next_query(1000.0) == g2.next_query(1000.0)

    def test_different_seed_differs(self):
        a = TrafficGen(RigConfig(seed=1, tenants=("a", "b", "c")))
        b = TrafficGen(RigConfig(seed=2, tenants=("a", "b", "c")))
        seq_a = [a.next_batch(0) for _ in range(20)]
        seq_b = [b.next_batch(0) for _ in range(20)]
        assert seq_a != seq_b

    def test_zipf_skew(self):
        """Recorded-shape traffic: the head tenant dominates."""
        g = TrafficGen(RigConfig(seed=3, tenants=("hot", "warm", "cold"),
                                 zipf_s=1.5))
        picks = [g.pick_tenant() for _ in range(600)]
        assert picks.count("hot") > picks.count("warm") > picks.count("cold")


class TestChaosSchedule:
    TARGETS = [("h0", "node0", "dbnode"), ("h1", "node1", "dbnode"),
               ("kv0", "kvd", "kvd"), ("hc", "agg", "aggregator")]

    def test_replayable(self):
        s1 = ChaosSchedule.generate(7, 30.0, self.TARGETS)
        s2 = ChaosSchedule.generate(7, 30.0, self.TARGETS)
        assert s1 == s2
        assert s1 != ChaosSchedule.generate(8, 30.0, self.TARGETS)

    def test_every_outage_has_a_closing_pair(self):
        events = ChaosSchedule.generate(7, 30.0, self.TARGETS)
        opens = {"kill": "restart", "partition": "heal"}
        by_target: dict[tuple, list] = {}
        for e in events:
            by_target.setdefault((e.agent, e.service), []).append(e)
        assert len(by_target) == len(self.TARGETS)
        for pair in by_target.values():
            assert len(pair) == 2
            assert opens[pair[0].action] == pair[1].action
            assert pair[1].t_s > pair[0].t_s

    def test_outage_windows_never_overlap(self):
        """One failure domain at a time: overlapping windows would kill
        both replicas of an RF=2 shard and turn an availability-by-design
        gap into a fake data-loss signal."""
        events = ChaosSchedule.generate(7, 30.0, self.TARGETS)
        windows = []
        open_at: dict[tuple, float] = {}
        for e in events:
            key = (e.agent, e.service)
            if e.action in ("kill", "partition"):
                open_at[key] = e.t_s
            else:
                windows.append((open_at.pop(key), e.t_s))
        windows.sort()
        for (s1, e1), (s2, _e2) in zip(windows, windows[1:]):
            assert e1 <= s2

    def test_partition_events_carry_fault_specs(self):
        events = ChaosSchedule.generate(123, 60.0, self.TARGETS,
                                        partition_frac=1.0)
        parts = [e for e in events if e.action == "partition"]
        assert parts and all(e.fault_spec for e in parts)


# ---------------------------------------------------------------------------
# histogram parsing (tier-1): the rig's p99s come from /metrics text


class TestHistogramParsing:
    def test_parse_matches_inprocess_quantile(self):
        from m3_tpu.utils.instrument import MetricsRegistry

        reg = MetricsRegistry()
        scope = reg.root_scope("coordinator").subscope(
            "tenant", namespace="parse_t")
        import random

        rng = random.Random(5)
        values = [rng.uniform(0.001, 0.2) for _ in range(500)]
        for v in values:
            scope.observe("request_seconds", v)
        text = reg.render_prometheus().decode()
        hist = rigmod.parse_histogram(
            text, "coordinator_tenant_request_seconds",
            {"namespace": "parse_t"})
        assert sum(hist[1]) == 500
        key = ("coordinator.tenant.request_seconds",
               (("namespace", "parse_t"),))
        want_ms = reg.histograms[key].quantile(0.99) * 1e3
        got_ms = rigmod.hist_p99_ms(hist)
        assert got_ms == pytest.approx(want_ms, rel=1e-6)

    def test_delta_windows(self):
        bounds = [0.1, 1.0]
        prev = (bounds, [5.0, 1.0, 0.0])
        cur = (bounds, [9.0, 1.0, 2.0])
        b, d = rigmod.hist_delta(prev, cur)
        assert b == bounds and d == [4.0, 0.0, 2.0]
        assert rigmod.hist_p99_ms((bounds, [0.0, 0.0, 0.0])) is None

    def test_label_filter_excludes_other_series(self):
        text = (
            'coordinator_tenant_request_seconds_bucket{namespace="x",le="1"} 3\n'
            'coordinator_tenant_request_seconds_bucket{namespace="x",le="+Inf"} 3\n'
            'coordinator_tenant_request_seconds_bucket{namespace="y",le="1"} 9\n'
            'coordinator_tenant_request_seconds_bucket{namespace="y",le="+Inf"} 9\n'
        )
        _b, counts = rigmod.parse_histogram(
            text, "coordinator_tenant_request_seconds", {"namespace": "x"})
        assert sum(counts) == 3


class TestNamespaceTimeUnit:
    """The registry knob the rig depends on: a namespace ingesting
    irregular ns timestamps must be able to declare a fine time unit, or
    snapshot/flush encode truncates to seconds and a restart silently
    collapses datapoints (the loss mode the rig's audit caught)."""

    def test_parse_time_unit(self):
        from m3_tpu.encoding.m3tsz.constants import TimeUnit
        from m3_tpu.services.coordinator import (
            namespace_options,
            parse_time_unit,
        )

        assert parse_time_unit("ns") is TimeUnit.NANOSECOND
        assert parse_time_unit("MS") is TimeUnit.MILLISECOND
        with pytest.raises(ValueError):
            parse_time_unit("fortnights")
        assert namespace_options(
            {"time_unit": "ns"}).write_time_unit is TimeUnit.NANOSECOND
        assert namespace_options({}).write_time_unit is TimeUnit.SECOND

    def test_ns_unit_snapshot_restore_roundtrip(self, tmp_path):
        """Irregular ns timestamps survive a snapshot -> restart ->
        restore cycle exactly when the namespace declares time_unit ns
        (with the WAL already reclaimed, the snapshot IS durability)."""
        from m3_tpu.services.coordinator import namespace_options
        from m3_tpu.storage.database import Database
        from m3_tpu.storage.options import DatabaseOptions

        opts = namespace_options({"time_unit": "ns"})
        base = 1_785_754_950_000_000_000
        points = [(base + i * 997_001, float(i)) for i in range(40)]

        db = Database(str(tmp_path / "d"), DatabaseOptions(n_shards=2))
        db.create_namespace("t", opts)
        db.open(now_ns=base)
        for t, v in points:
            db.write_tagged("t", b"m", [(b"k", b"v")], t, v)
        db.snapshot(base + 1)
        # simulate the WAL being reclaimed: durability rests on snapshots
        import glob
        import os

        for f in glob.glob(str(tmp_path / "d" / "commitlog" / "t" / "*")):
            os.remove(f)
        db.close()

        db2 = Database(str(tmp_path / "d"), DatabaseOptions(n_shards=2))
        db2.create_namespace("t", opts)
        db2.open(now_ns=base + 2)
        try:
            from m3_tpu.utils.ident import tags_to_id

            sid = tags_to_id(b"m", [(b"k", b"v")])
            got = {(d.timestamp_ns, d.value)
                   for d in db2.read("t", sid, 0, 1 << 62)}
            assert got == set(points)  # ns-exact, nothing collapsed
        finally:
            db2.close()


# ---------------------------------------------------------------------------
# in-process rig smoke (tier-1): the whole loop, no subprocesses


class TestInProcessRigSmoke:
    @pytest.fixture
    def smoke(self, tmp_path):
        from m3_tpu.query.api import CoordinatorAPI
        from m3_tpu.storage import limits as storage_limits
        from m3_tpu.storage.database import Database
        from m3_tpu.storage.options import DatabaseOptions
        from m3_tpu.utils.tenantlimits import TenantAdmission, TenantQuota

        db = Database(str(tmp_path / "data"), DatabaseOptions(n_shards=2))
        for t in ("smokeA", "smokeB"):
            db.create_namespace(t)
        db.open()
        api = CoordinatorAPI(db, "smokeA")
        api.admission = TenantAdmission(
            {"smokeA": TenantQuota(queries_per_sec=3, burst_s=1.0),
             "smokeB": TenantQuota(queries_per_sec=10_000)},
            cardinality_source=lambda ns: storage_limits.live_series(db, ns))
        yield db, api
        db.close()

    def test_smoke_run(self, smoke):
        db, api = smoke
        cfg = RigConfig(seed=42, tenants=("smokeA", "smokeB"), zipf_s=1.0,
                        series_per_tenant=8, batch_size=8,
                        write_interval_s=0.02, query_interval_s=0.02,
                        duration_s=2.0)
        ledger = WriteLedger()
        rig = Rig(cfg, rigmod.db_write_fn(db), rigmod.api_query_fn(api),
                  ledger=ledger)
        report = rig.run()

        # load actually flowed and every acked write reads back
        assert report["acked_total"] > 100
        verify = ledger.verify(rigmod.db_fetch_fn(db))
        assert verify["checked"] == report["acked_total"]
        assert verify["missing"] == []

        # the saturated tenant was shed with Retry-After; the steady
        # tenant was never shed
        a = report["tenants"]["smokeA"]
        b = report["tenants"]["smokeB"]
        assert a["queries_shed"] > 0
        assert report["retry_after_seen"] > 0
        assert b["queries_shed"] == 0
        assert b["queries_ok"] > 0

        # server-side per-tenant histogram (the PR-4 family) carries
        # B's latency; p99 parsed from the exposition text
        from m3_tpu.utils.instrument import default_registry

        text = default_registry().render_prometheus().decode()
        hist = rigmod.parse_histogram(
            text, "coordinator_tenant_request_seconds",
            {"namespace": "smokeB"})
        assert sum(hist[1]) >= b["queries_ok"]
        p99 = rigmod.hist_p99_ms(hist)
        assert p99 is not None and p99 < 5000.0

    def test_ledger_detects_loss(self, smoke):
        """The verifier is only evidence if it can FAIL: a datapoint the
        reader does not return must be reported missing."""
        db, _api = smoke
        ledger = WriteLedger()
        entries = [(b"rig_metric_0", ((b"tenant", b"smokeA"),), 10**9, 1.5)]
        ledger.record("smokeA", entries, [None])
        report = ledger.verify(lambda *a: [])
        assert report["checked"] == 1
        assert len(report["missing"]) == 1
        report2 = ledger.verify(lambda *a: [(10**9, 1.5)])
        assert report2["missing"] == []


# ---------------------------------------------------------------------------
# cardinality-explosion episode (tier-1, in-process): index churn under
# live reads — the ISSUE-16 rig lane for the device-compiled index


class TestCardinalityChurnEpisode:
    """A tenant whose writes keep minting brand-new series (the
    ``churn_per_batch`` knob: monotonically-unique churn tags) drives
    continuous index ingest and segment churn. The episode's claim: the
    read path stays bounded — client p99 holds under the explosion, no
    query errors — while the live-series population multiplies."""

    def test_churn_minting_deterministic_and_unique(self):
        cfg = RigConfig(seed=9, tenants=("a", "b"), batch_size=8,
                        churn_per_batch=4)
        g1, g2 = TrafficGen(cfg), TrafficGen(cfg)
        seen = set()
        minted = 0
        for _ in range(30):
            batch = g1.next_batch(0)
            assert batch == g2.next_batch(0)  # same seed, same sequence
            for name, tags, _t, _v in batch[1]:
                if b"churn" in dict(tags):
                    minted += 1
                    seen.add((name, tags))
        # every churn entry is a NEW series identity, never a repeat
        assert minted >= 30 * cfg.churn_per_batch
        assert len(seen) == minted

    def test_bounded_read_p99_under_index_churn(self, tmp_path):
        from m3_tpu.query.api import CoordinatorAPI
        from m3_tpu.storage import limits as storage_limits
        from m3_tpu.storage.database import Database
        from m3_tpu.storage.options import DatabaseOptions

        db = Database(str(tmp_path / "data"), DatabaseOptions(n_shards=2))
        db.create_namespace("churnT")
        db.open()
        api = CoordinatorAPI(db, "churnT")
        try:
            before = storage_limits.live_series(db, "churnT")
            cfg = RigConfig(seed=77, tenants=("churnT",), zipf_s=1.0,
                            series_per_tenant=8, batch_size=16,
                            churn_per_batch=12, write_interval_s=0.01,
                            query_interval_s=0.02, duration_s=2.5)
            rig = Rig(cfg, rigmod.db_write_fn(db), rigmod.api_query_fn(api))
            report = rig.run()
            after = storage_limits.live_series(db, "churnT")

            # the explosion actually happened: the live-series population
            # grew by hundreds of freshly minted identities
            assert after - before > 300
            st = report["tenants"]["churnT"]
            assert st["writes_acked"] > 500 and st["write_errors"] == 0

            # and reads stayed healthy THROUGH the churn: all served, no
            # errors, client p99 inside the default SLO bound
            assert st["queries_ok"] > 20
            assert st["query_errors"] == 0
            assert st["client_p99_ms"] is not None
            assert st["client_p99_ms"] < cfg.slo_p99_ms
        finally:
            db.close()


# ---------------------------------------------------------------------------
# process-level chaos lane (`run_tests.sh rig`; marked chaos -> not tier-1)


def _cpu_env():
    import pathlib

    return {
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "PYTHONPATH": str(pathlib.Path(__file__).resolve().parents[1]),
    }


@pytest.mark.chaos
class TestProcessRig:
    def test_production_rig_full(self, tmp_path):
        """The acceptance run: seeded kill/partition schedule against
        real spawned processes under live load — zero acked-write loss,
        warning-bearing reads during the outage, runtime quota push via
        kvd, noisy tenant shed with 429 while steady tenant's
        pair-median p99 (server histograms) holds the SLO."""
        seconds = float(os.environ.get("M3_TPU_RIG_SECONDS", "20"))
        seed = int(os.environ.get("M3_TPU_RIG_SEED", "7"))
        report = rigmod.run_production_rig(
            str(tmp_path / "rig"), seconds=seconds, seed=seed,
            slo_p99_ms=5000.0)

        # chaos actually happened, and every action round-tripped
        assert report["chaos_executed"], report.get("chaos_errors")
        assert not report["chaos_errors"], report["chaos_errors"]

        # zero acked-write loss across SIGKILLs and partitions
        assert report["verify"]["acked"] > 0
        assert report["verify"]["missing"] == [], report["verify"]
        assert report["verify"]["checked"] == report["verify"]["acked"]

        # the ReadWarning contract surfaced during the outage windows
        warnings = sum(t["warnings"]
                       for t in report["phase1"]["tenants"].values())
        assert warnings >= 1, report["phase1"]

        # anti-entropy convergence: every replica pair reached
        # per-(shard, block) rollup-digest equality within the repair
        # cycle budget — driven by the nodes' continuous daemons, not by
        # the rig invoking repair
        conv = report["convergence"]
        assert conv["converged"], conv
        assert conv["replica_pairs"] > 0, conv
        assert conv["cycles_used"] <= conv["budget_cycles"] * 2, conv

        # noisy-tenant isolation under a node kill: quota pushed through
        # the kvd metadata plane mid-run started shedding the noisy
        # tenant; the steady tenant held its SLO (pair-median p99 from
        # the per-tenant server histograms)
        noisy = report["noisy_phase"]
        assert noisy["noisy_sheds"] > 0, noisy
        assert noisy["steady_sheds"] == 0, noisy
        assert noisy["steady_pair_median_p99_ms"] is not None, noisy
        assert noisy["steady_pair_median_p99_ms"] <= noisy["slo_p99_ms"], noisy

        # the soak trajectory artifact (profiling & saturation plane):
        # sampled rows with QPS/p99/RSS, a NON-EMPTY contended-lock
        # table from the armed lock-wait profiler, and >= 1 watchdog
        # stall event (the drill wedges a live dbnode's tick loop; its
        # own watchdog must report it with the wedged thread's stack)
        traj = report["trajectory"]
        assert traj["schema"] == rigmod.TrajectoryRecorder.SCHEMA
        assert len(traj["samples"]) >= 3, traj["samples"]
        assert any(s["rss_bytes"] for s in traj["samples"]), traj["samples"]
        assert traj["contended_locks"], "no contended locks recorded"
        assert traj["stall_events"], report.get("stall_drill")
        drill = report["stall_drill"]
        assert drill["events"], drill
        assert any("dbnode.py" in (e.get("stack") or "")
                   for e in drill["events"]), drill

        # every process is back at the end
        assert all(v == "ok" for v in report["final_heartbeats"].values())

    def test_elasticity_episode(self, tmp_path):
        """ROADMAP #6(b): add-node -> paced drain -> rolling restart
        under live load with a chaos schedule on the kvd/aggregator
        planes. The placement CAS is the rig's only lever — the nodes'
        handoff controllers stream, digest-verify, and cut over. Budget
        rides M3_TPU_RIG_SECONDS like the production run."""
        seconds = float(os.environ.get("M3_TPU_RIG_SECONDS", "20"))
        seed = int(os.environ.get("M3_TPU_RIG_SEED", "7"))
        report = rigmod.run_elasticity_episode(
            str(tmp_path / "rig"), seconds=max(10.0, seconds), seed=seed,
            slo_p99_ms=5000.0)

        # the topology actually churned: every verb ran and landed on
        # the trajectory timeline
        acts = [e["action"]
                for e in report["trajectory"]["topology_events"]]
        for want in ("add_node", "handoff_settled", "drain", "drained",
                     "restart"):
            assert want in acts, acts
        assert not report["chaos_errors"], report["chaos_errors"]

        # zero acked-write loss through add/drain/restart
        assert report["verify"]["acked"] > 0
        assert report["verify"]["missing"] == [], report["verify"]
        assert report["verify"]["checked"] == report["verify"]["acked"]

        # the handoff controllers did the work, observable on the new
        # /debug/placement surface (per-shard records, cutover totals)
        completed = sum(
            doc.get("handoff", {}).get("totals", {}).get("completed", 0)
            for doc in report["handoff_status"].values())
        assert completed > 0, report["handoff_status"]

        # the drained node is GONE and every shard ended AVAILABLE on
        # the post-change owners
        final = report["final_placement"]
        assert report["drained_node"] not in final, final
        assert final, final
        assert all(st == "AVAILABLE" for shards in final.values()
                   for st in shards.values()), final

        # bounded read p99 while the topology churned
        for t, st in report["phase"]["tenants"].items():
            if st["client_p99_ms"] is not None:
                assert st["client_p99_ms"] < 5000.0, (t, st)

        # anti-entropy convergence on the post-change replica pairs
        conv = report["convergence"]
        assert conv["converged"], conv
        assert conv["replica_pairs"] > 0, conv

    def test_standing_rules_episode(self, tmp_path):
        """ISSUE-18: standing recording rules + retention tiers under
        the full chaos schedule. The ruleset lands through KV mid-load;
        the coordinator evaluates against the quorum cluster while
        dbnodes, a kvd replica and the aggregator die and heal."""
        seconds = float(os.environ.get("M3_TPU_RIG_SECONDS", "20"))
        seed = int(os.environ.get("M3_TPU_RIG_SEED", "11"))
        report = rigmod.run_standing_rules_episode(
            str(tmp_path / "rig"), seconds=max(10.0, seconds), seed=seed,
            slo_p99_ms=5000.0)

        assert report["chaos_executed"], report.get("chaos_errors")
        assert not report["chaos_errors"], report["chaos_errors"]

        # zero acked-write loss for the raw load under chaos
        assert report["verify"]["acked"] > 0
        assert report["verify"]["missing"] == [], report["verify"]
        assert report["verify"]["checked"] == report["verify"]["acked"]

        # registry-sync: the rule-created tier namespace landed in KV
        # with its resolution (and WAL-replayable retention) recorded
        entry = report["registry_entry"]
        assert entry and entry["resolution"] == "1s", entry
        assert "complete" not in entry, entry  # standing-only: never

        # every rule recovered error-free with a caught-up watermark,
        # including the absent-input rule (evaluates, writes nothing)
        rules = report["standing_status"]["rules"]
        assert set(rules) == {"std:rig0:sum", "std:rig1:by_sid",
                              "std:rig2:avg", "std:absent"}, rules
        assert all(st["error"] is None and st["evals"] > 0
                   for st in rules.values()), rules

        # outputs exist and the aggregated/raw dual-write legs agree
        # point-for-point after the repair daemons converged
        assert report["output_points"] > 0, report["output_audit"]
        assert report["leg_parity_ok"], report["output_audit"]
        by_sid = report["output_audit"]["std:rig1:by_sid"]
        assert by_sid["agg_series"] >= 1, by_sid

        # convergence covered the tenants AND the rule-created namespace
        conv = report["convergence"]
        assert conv["converged"], conv
        assert conv["replica_pairs"] > 0, conv

        # bounded rule-eval lag, annotated onto the trajectory
        assert report["rule_eval_lag_p99_s"] is not None
        assert report["rule_eval_lag_p99_s"] <= report["lag_bound_s"]
        lag_events = [e for e in report["trajectory"]["topology_events"]
                      if e["action"] == "rule_eval_lag"]
        assert lag_events, report["trajectory"]["topology_events"]

        # misrouting honesty gate: an incomplete tier is never read
        assert report["no_misrouted_reads"], report["tier_reads"]
        assert report["tier_reads"], "no tier-routing decisions recorded"

    def test_crash_rule_kills_real_process(self, tmp_path):
        """The M3_TPU_FAULTS_EXIT satellite end to end: a crash-mode
        fault rule firing inside a REAL dbnode makes the process exit
        137 (observable death), not a 500 from a process that lives on."""
        import urllib.request

        from m3_tpu.tools.em import AgentClient, ClusterEnv, EmAgent

        agent = EmAgent(str(tmp_path / "host"), "127.0.0.1:0",
                        agent_id="host")
        client = AgentClient(f"http://127.0.0.1:{agent.port}")
        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        try:
            client.put_file("node.yml", (
                f"db:\n  path: {tmp_path}/host/data\n  n_shards: 2\n"
                f"  namespaces:\n    - name: default\n"
                f"http:\n  host: 127.0.0.1\n  port: {port}\n"
                f"tick_interval_s: 5.0\n"))
            client.start("node", "m3_tpu.services.dbnode", "node.yml", env={
                **_cpu_env(),
                "M3_TPU_FAULTS": "dbnode.handle=crash:n3",
                "M3_TPU_FAULTS_EXIT": "1",
            })
            ClusterEnv.wait_until(
                lambda: rigmod._http_ok(f"http://127.0.0.1:{port}/health"),
                timeout_s=60, desc="node up")

            def read_once():
                url = (f"http://127.0.0.1:{port}/read?namespace=default"
                       f"&series_id=c2lk&start_ns=0&end_ns=1")
                try:
                    urllib.request.urlopen(url, timeout=5).read()
                except Exception:  # noqa: BLE001 - the 3rd request dies
                    pass           # mid-flight: torn connection expected

            for _ in range(3):
                read_once()
            ClusterEnv.wait_until(
                lambda: not client.status("node")["running"],
                timeout_s=30, desc="process death from crash rule")
            assert client.status("node")["returncode"] == 137

            # restart with a clean plan: the node serves again
            client.start("node", env=_cpu_env())
            ClusterEnv.wait_until(
                lambda: rigmod._http_ok(f"http://127.0.0.1:{port}/health"),
                timeout_s=60, desc="node back after crash")
        finally:
            try:
                client.stop("node", sig="SIGKILL")
            except Exception:  # noqa: BLE001
                pass
            agent.close()

    def test_start_surfaces_death_diagnostics(self, tmp_path):
        """The em satellite: a child dying inside the startup grace
        window raises AgentError WITH the log tail (today's alternative
        is wait_until timing out blind)."""
        from m3_tpu.tools.em import AgentClient, AgentError, EmAgent

        agent = EmAgent(str(tmp_path / "host"), "127.0.0.1:0",
                        agent_id="host")
        client = AgentClient(f"http://127.0.0.1:{agent.port}")
        try:
            client.put_file("bad.yml", "db: [unclosed\n  nonsense")
            with pytest.raises(AgentError) as ei:
                client.start("svc", "m3_tpu.services.dbnode", "bad.yml",
                             env=_cpu_env(), grace_s=90.0)
            msg = str(ei.value)
            assert "exited rc=" in msg
            assert "log tail" in msg
            # the tail carries the actual failure (yaml/config traceback)
            assert "Traceback" in msg or "Error" in msg
        finally:
            agent.close()
