"""PromQL parser + engine tests.

Engine numeric cases are hand-computed against upstream Prometheus
semantics (extrapolated rate, lookback staleness, aggregation grouping,
vector matching) — the comparator role of SURVEY.md §4.6 at unit scale.
"""

import math

import numpy as np
import pytest

from m3_tpu.index.query import MatchType
from m3_tpu.query import promql
from m3_tpu.query.engine import Engine, Scalar, Vector
from m3_tpu.query.promql import (
    AggregateExpr,
    BinaryExpr,
    Call,
    MatrixSelector,
    NumberLiteral,
    ParseError,
    VectorSelector,
    parse,
)
from m3_tpu.storage.database import Database
from m3_tpu.storage.options import DatabaseOptions

MIN = 60 * 10**9
HOUR = 3600 * 10**9
START = 1_599_998_400_000_000_000


class TestParser:
    def test_selector(self):
        e = parse('http_requests_total{job="api", code=~"5.."}')
        assert isinstance(e, VectorSelector)
        assert e.name == "http_requests_total"
        assert [(m.match_type, m.name, m.value) for m in e.matchers] == [
            (MatchType.EQUAL, b"__name__", b"http_requests_total"),
            (MatchType.EQUAL, b"job", b"api"),
            (MatchType.REGEXP, b"code", b"5.."),
        ]

    def test_matrix_and_offset(self):
        e = parse("rate(foo[5m] offset 1h)")
        assert isinstance(e, Call) and e.func == "rate"
        ms = e.args[0]
        assert isinstance(ms, MatrixSelector)
        assert ms.range_ns == 5 * MIN
        assert ms.selector.offset_ns == HOUR

    def test_precedence(self):
        e = parse("1 + 2 * 3 ^ 2")
        assert isinstance(e, BinaryExpr) and e.op == "+"
        assert e.rhs.op == "*"
        assert e.rhs.rhs.op == "^"

    def test_right_assoc_pow(self):
        e = parse("2 ^ 3 ^ 2")
        assert e.op == "^" and isinstance(e.lhs, NumberLiteral)
        assert e.rhs.op == "^"

    def test_aggregate_by(self):
        e = parse("sum by (job, dc) (rate(x[1m]))")
        assert isinstance(e, AggregateExpr)
        assert e.op == "sum" and e.grouping == ("job", "dc") and not e.without
        e2 = parse("sum(rate(x[1m])) without (host)")
        assert e2.without and e2.grouping == ("host",)

    def test_quantile_param(self):
        e = parse("quantile(0.9, x)")
        assert isinstance(e.param, NumberLiteral) and e.param.value == 0.9

    def test_bool_and_matching(self):
        e = parse("a > bool b")
        assert e.bool_mode
        e = parse("a / on(job) group_left(instance) b")
        assert e.matching.on and e.matching.labels == ("job",)
        assert e.matching.group_left and e.matching.include == ("instance",)

    def test_durations(self):
        assert promql.parse_duration("1h30m") == HOUR + 30 * MIN
        assert promql.parse_duration("90s") == 90 * 10**9
        assert promql.parse_duration("100ms") == 10**8

    def test_errors(self):
        for bad in ["sum(", "foo{", "foo[]", "foo[5m", "1 +", "{}", "foo bar"]:
            with pytest.raises(ParseError):
                parse(bad)

    def test_metric_with_colons(self):
        e = parse("job:request_rate:sum5m")
        assert e.name == "job:request_rate:sum5m"


@pytest.fixture
def db(tmp_path):
    db = Database(str(tmp_path / "db"), DatabaseOptions(n_shards=4))
    db.create_namespace("default")
    db.open(START)
    yield db
    db.close()


def write_series(db, name, tags, points):
    for t, v in points:
        db.write_tagged("default", name, tags, t, v)


class TestEngine:
    def test_instant_selector_lookback(self, db):
        write_series(db, b"up", [(b"job", b"a")], [(START + 10 * 10**9, 1.0)])
        eng = Engine(db)
        v, ts = eng.query_range("up", START, START + 5 * MIN, MIN)
        assert isinstance(v, Vector) and len(v.labels) == 1
        # sample at t=10s is visible for 5m of lookback
        assert not np.isnan(v.values[0, 1])  # t = 60s
        assert not np.isnan(v.values[0, 5])  # t = 300s
        assert np.isnan(v.values[0, 0])  # t = 0 (before sample)

    def test_rate_counter(self, db):
        # perfect 1/s counter sampled every 15s for 10m
        pts = [(START + i * 15 * 10**9, float(i * 15)) for i in range(41)]
        write_series(db, b"reqs_total", [(b"job", b"a")], pts)
        eng = Engine(db)
        v, _ = eng.query_range("rate(reqs_total[2m])", START + 5 * MIN, START + 10 * MIN, MIN)
        np.testing.assert_allclose(v.values[0], 1.0, rtol=1e-9)
        # name is dropped
        assert b"__name__" not in v.labels[0]

    def test_rate_counter_reset(self, db):
        pts = [
            (START + 0 * MIN, 0.0),
            (START + 1 * MIN, 60.0),
            (START + 2 * MIN, 120.0),
            (START + 3 * MIN, 20.0),  # reset
            (START + 4 * MIN, 80.0),
        ]
        write_series(db, b"c", [], pts)
        eng = Engine(db)
        v, _ = eng.query_range("increase(c[4m])", START + 4 * MIN, START + 4 * MIN, MIN)
        # window (0,4m] excludes the t=0 sample: samples 60,120,20,80 adjust
        # to 60,120,140,200 -> result 140 over 3m sampled; extrapolation:
        # durToStart=60s < 66s threshold, durToZero=180*(60/140)=77s > 60s,
        # factor (180+60)/180 = 4/3 -> 140 * 4/3 = 186.666..
        np.testing.assert_allclose(v.values[0, 0], 140 * 4 / 3, rtol=1e-9)

    def test_increase_extrapolation(self, db):
        # samples at 15..45s in a 60s window: extrapolates to full window
        pts = [(START + s * 10**9, float(s)) for s in (15, 30, 45)]
        write_series(db, b"c2", [], pts)
        eng = Engine(db)
        v, _ = eng.query_range("increase(c2[1m])", START + MIN, START + MIN, MIN)
        # upstream: sampled=30s, durToStart=15>16.5? avg=15, thresh=16.5,
        # both 15<16.5 -> extrapolate full: 30 * (30+15+15)/30 = 60... but
        # zero-point: durToZero = 30*(15/30)=15 == durToStart -> unchanged
        np.testing.assert_allclose(v.values[0, 0], 60.0, rtol=1e-9)

    def test_avg_over_time(self, db):
        pts = [(START + i * 10 * 10**9, float(i)) for i in range(12)]
        write_series(db, b"g", [], pts)
        eng = Engine(db)
        v, _ = eng.query_range("avg_over_time(g[1m])", START + MIN, START + MIN, MIN)
        # window (0s,60s]: samples at 10..60 -> values 1..6 -> mean 3.5
        np.testing.assert_allclose(v.values[0, 0], 3.5)

    def test_min_max_last_over_time(self, db):
        pts = [(START + i * 10 * 10**9, v) for i, v in enumerate([5, 1, 9, 2, 7, 3])]
        write_series(db, b"g2", [], pts)
        eng = Engine(db)
        for fn, want in [("min_over_time", 1.0), ("max_over_time", 9.0),
                         ("last_over_time", 3.0), ("count_over_time", 5.0),
                         ("sum_over_time", 22.0)]:
            v, _ = eng.query_range(f"{fn}(g2[50s])", START + 50 * 10**9,
                                   START + 50 * 10**9, MIN)
            np.testing.assert_allclose(v.values[0, 0], want, err_msg=fn)

    def test_aggregation_sum_by(self, db):
        for job, dc, val in [(b"a", b"e", 1.0), (b"a", b"w", 2.0), (b"b", b"e", 4.0)]:
            write_series(db, b"m", [(b"job", job), (b"dc", dc)], [(START + 10**9, val)])
        eng = Engine(db)
        v, _ = eng.query_range("sum by (job) (m)", START + MIN, START + MIN, MIN)
        got = {lb[b"job"]: v.values[i, 0] for i, lb in enumerate(v.labels)}
        assert got == {b"a": 3.0, b"b": 4.0}
        v, _ = eng.query_range("sum(m)", START + MIN, START + MIN, MIN)
        assert v.values[0, 0] == 7.0 and v.labels[0] == {}
        v, _ = eng.query_range("sum without (dc) (m)", START + MIN, START + MIN, MIN)
        got = {lb[b"job"]: v.values[i, 0] for i, lb in enumerate(v.labels)}
        assert got == {b"a": 3.0, b"b": 4.0}

    def test_aggregation_variants(self, db):
        for i, val in enumerate([1.0, 2.0, 3.0, 4.0]):
            write_series(db, b"m2", [(b"i", str(i).encode())], [(START + 10**9, val)])
        eng = Engine(db)
        cases = {
            "min(m2)": 1.0,
            "max(m2)": 4.0,
            "count(m2)": 4.0,
            "avg(m2)": 2.5,
            "stddev(m2)": np.std([1, 2, 3, 4]),
            "quantile(0.5, m2)": 2.5,
        }
        for q, want in cases.items():
            v, _ = eng.query_range(q, START + MIN, START + MIN, MIN)
            np.testing.assert_allclose(v.values[0, 0], want, err_msg=q)

    def test_topk(self, db):
        for i, val in enumerate([1.0, 5.0, 3.0]):
            write_series(db, b"m3", [(b"i", str(i).encode())], [(START + 10**9, val)])
        eng = Engine(db)
        v, _ = eng.query_range("topk(2, m3)", START + MIN, START + MIN, MIN)
        got = sorted(v.values[:, 0])
        assert got == [3.0, 5.0]

    def test_binary_vector_scalar(self, db):
        write_series(db, b"m4", [], [(START + 10**9, 10.0)])
        eng = Engine(db)
        v, _ = eng.query_range("m4 * 2 + 1", START + MIN, START + MIN, MIN)
        assert v.values[0, 0] == 21.0
        v, _ = eng.query_range("m4 > 5", START + MIN, START + MIN, MIN)
        assert v.values[0, 0] == 10.0  # filter keeps value
        v, _ = eng.query_range("m4 > bool 5", START + MIN, START + MIN, MIN)
        assert v.values[0, 0] == 1.0
        v, _ = eng.query_range("m4 < 5", START + MIN, START + MIN, MIN)
        assert len(v.labels) == 0  # filtered out entirely

    def test_binary_vector_vector_matching(self, db):
        write_series(db, b"errs", [(b"job", b"a")], [(START + 10**9, 10.0)])
        write_series(db, b"reqs", [(b"job", b"a")], [(START + 10**9, 100.0)])
        write_series(db, b"errs", [(b"job", b"b")], [(START + 10**9, 1.0)])
        write_series(db, b"reqs", [(b"job", b"b")], [(START + 10**9, 50.0)])
        eng = Engine(db)
        v, _ = eng.query_range("errs / reqs", START + MIN, START + MIN, MIN)
        got = {lb[b"job"]: v.values[i, 0] for i, lb in enumerate(v.labels)}
        assert got == {b"a": 0.1, b"b": 0.02}
        assert all(b"__name__" not in lb for lb in v.labels)

    def test_set_ops(self, db):
        write_series(db, b"x", [(b"k", b"1")], [(START + 10**9, 1.0)])
        write_series(db, b"x", [(b"k", b"2")], [(START + 10**9, 2.0)])
        write_series(db, b"y", [(b"k", b"2")], [(START + 10**9, 9.0)])
        eng = Engine(db)
        v, _ = eng.query_range("x and y", START + MIN, START + MIN, MIN)
        assert len(v.labels) == 1 and v.labels[0][b"k"] == b"2"
        v, _ = eng.query_range("x unless y", START + MIN, START + MIN, MIN)
        assert len(v.labels) == 1 and v.labels[0][b"k"] == b"1"
        v, _ = eng.query_range("x or y", START + MIN, START + MIN, MIN)
        assert len(v.labels) == 2

    def test_math_functions(self, db):
        write_series(db, b"m5", [], [(START + 10**9, -4.0)])
        eng = Engine(db)
        v, _ = eng.query_range("abs(m5)", START + MIN, START + MIN, MIN)
        assert v.values[0, 0] == 4.0
        v, _ = eng.query_range("clamp_min(m5, 0)", START + MIN, START + MIN, MIN)
        assert v.values[0, 0] == 0.0
        v, _ = eng.query_range("sqrt(abs(m5))", START + MIN, START + MIN, MIN)
        assert v.values[0, 0] == 2.0

    def test_scalar_and_time(self, db):
        eng = Engine(db)
        s, ts = eng.query_range("42", START, START + 2 * MIN, MIN)
        assert isinstance(s, Scalar)
        np.testing.assert_array_equal(s.values, [42, 42, 42])
        s, _ = eng.query_range("time()", START, START, MIN)
        assert s.values[0] == START / 1e9

    def test_histogram_quantile(self, db):
        # classic histogram: buckets 0.1 / 0.5 / +Inf with cum counts 10/30/40
        for le, cnt in [(b"0.1", 10.0), (b"0.5", 30.0), (b"+Inf", 40.0)]:
            write_series(db, b"lat_bucket", [(b"le", le)], [(START + 10**9, cnt)])
        eng = Engine(db)
        v, _ = eng.query_range(
            "histogram_quantile(0.5, lat_bucket)", START + MIN, START + MIN, MIN
        )
        # rank = 20 -> second bucket: 0.1 + (0.5-0.1)*(10/20) = 0.3
        np.testing.assert_allclose(v.values[0, 0], 0.3)

    def test_absent(self, db):
        eng = Engine(db)
        v, _ = eng.query_range('absent(nothing{job="x"})', START + MIN, START + MIN, MIN)
        assert v.values[0, 0] == 1.0 and v.labels[0] == {b"job": b"x"}

    def test_offset(self, db):
        write_series(db, b"m6", [], [(START + 10**9, 7.0)])
        eng = Engine(db)
        v, _ = eng.query_range("m6 offset 10m", START + 11 * MIN, START + 11 * MIN, MIN)
        assert v.values[0, 0] == 7.0

    def test_label_replace(self, db):
        write_series(db, b"m7", [(b"host", b"web-1")], [(START + 10**9, 1.0)])
        eng = Engine(db)
        v, _ = eng.query_range(
            'label_replace(m7, "idx", "$1", "host", "web-(.*)")',
            START + MIN, START + MIN, MIN,
        )
        assert v.labels[0][b"idx"] == b"1"

    def test_deriv(self, db):
        pts = [(START + i * 10 * 10**9, 2.0 * i * 10) for i in range(7)]
        write_series(db, b"m8", [], pts)
        eng = Engine(db)
        v, _ = eng.query_range("deriv(m8[1m])", START + MIN, START + MIN, MIN)
        np.testing.assert_allclose(v.values[0, 0], 2.0, rtol=1e-9)


class TestParserRegressions:
    def test_metric_starting_with_inf_nan(self):
        e = parse("infra_up")
        assert e.name == "infra_up"
        e = parse("nano_seconds_total")
        assert e.name == "nano_seconds_total"
        assert parse("inf").value == float("inf")

    def test_utf8_label_values(self):
        e = parse('m{city="café", note="tab\\there"}')
        vals = {m.name: m.value for m in e.matchers}
        assert vals[b"city"] == "café".encode()
        assert vals[b"note"] == b"tab\there"


class TestGroupLeftLabels:
    def test_group_left_keeps_many_side_labels(self, db):
        write_series(db, b"errs", [(b"job", b"j"), (b"code", b"500")],
                     [(START + 10**9, 5.0)])
        write_series(db, b"errs", [(b"job", b"j"), (b"code", b"404")],
                     [(START + 10**9, 10.0)])
        write_series(db, b"reqs", [(b"job", b"j")], [(START + 10**9, 100.0)])
        eng = Engine(db)
        v, _ = eng.query_range("errs / ignoring(code) group_left reqs",
                               START + MIN, START + MIN, MIN)
        got = {lb[b"code"]: v.values[i, 0] for i, lb in enumerate(v.labels)}
        assert got == {b"500": 0.05, b"404": 0.1}


class TestQueryLimits:
    def test_series_and_datapoint_limits(self, db):
        from m3_tpu.query.engine import QueryLimitError, QueryLimits

        for i in range(10):
            write_series(db, b"lim", [(b"i", str(i).encode())],
                         [(START + j * 10**9, 1.0) for j in range(1, 6)])
        eng = Engine(db, limits=QueryLimits(max_series=5))
        with pytest.raises(QueryLimitError, match="series"):
            eng.query_range("lim", START + MIN, START + MIN, MIN)
        eng = Engine(db, limits=QueryLimits(max_datapoints=20))
        with pytest.raises(QueryLimitError, match="datapoints"):
            eng.query_range("lim", START + MIN, START + MIN, MIN)
        eng = Engine(db, limits=QueryLimits(max_steps=10))
        with pytest.raises(QueryLimitError, match="steps"):
            eng.query_range("lim", START, START + HOUR, MIN)
        # generous limits pass
        eng = Engine(db, limits=QueryLimits(max_series=100,
                                            max_datapoints=1000, max_steps=100))
        v, _ = eng.query_range("lim", START + MIN, START + MIN, MIN)
        assert len(v.labels) == 10

    def test_budget_shared_across_selectors(self, db):
        from m3_tpu.query.engine import QueryLimitError, QueryLimits

        for name in (b"la", b"lb", b"lc"):
            for i in range(4):
                write_series(db, name, [(b"i", str(i).encode())],
                             [(START + 10**9, 1.0)])
        # 12 series total across three selectors; per-selector 4 <= 10 but
        # the shared budget must trip
        eng = Engine(db, limits=QueryLimits(max_series=10))
        with pytest.raises(QueryLimitError, match="series"):
            eng.query_range("la + lb + lc" if False else "sum(la) + sum(lb) + sum(lc)",
                            START + MIN, START + MIN, MIN)

    def test_limits_cover_graphite_render(self, db):
        """Budgets are enforced in the storage read path, so Graphite
        /render draws from the same per-request budget as PromQL."""
        import json as _json
        import urllib.error
        import urllib.request

        from m3_tpu.query.api import CoordinatorAPI
        from m3_tpu.query.engine import QueryLimits
        from m3_tpu.query.graphite import path_to_tags

        for i in range(8):
            path = f"web.host{i}.cpu".encode()
            write_series(db, path, path_to_tags(path), [(START + 10**9, 1.0)])
        api = CoordinatorAPI(db, limits=QueryLimits(max_series=3))
        port = api.serve(port=0)
        try:
            url = (f"http://127.0.0.1:{port}/render?target=web.*.cpu"
                   f"&from={START//10**9}&until={START//10**9 + 120}")
            try:
                urllib.request.urlopen(url)
                raise AssertionError("expected query-limit rejection")
            except urllib.error.HTTPError as e:
                assert e.code == 422
                body = _json.loads(e.read())
                assert "limit" in body["error"]
        finally:
            api.shutdown()

    def test_limits_cover_remote_read(self, db):
        import json as _json
        import urllib.error
        import urllib.request

        from m3_tpu.query.api import CoordinatorAPI
        from m3_tpu.query.engine import QueryLimits
        from m3_tpu.utils import protowire, snappy

        for i in range(8):
            write_series(db, b"rr", [(b"i", str(i).encode())],
                         [(START + 10**9, 1.0)])
        api = CoordinatorAPI(db, limits=QueryLimits(max_series=3))
        port = api.serve(port=0)
        try:
            req = protowire.encode_read_request(
                [(START // 10**6, START // 10**6 + 120_000,
                  [protowire.PromMatcher(0, b"__name__", b"rr")])]
            )
            body = snappy.compress(req)
            try:
                urllib.request.urlopen(urllib.request.Request(
                    f"http://127.0.0.1:{port}/api/v1/prom/remote/read",
                    data=body, method="POST",
                    headers={"Content-Type": "application/x-protobuf"},
                ))
                raise AssertionError("expected query-limit rejection")
            except urllib.error.HTTPError as e:
                assert e.code == 422
                assert "limit" in _json.loads(e.read())["error"]
            # an under-limit remote read succeeds and round-trips
            req = protowire.encode_read_request(
                [(START // 10**6, START // 10**6 + 120_000,
                  [protowire.PromMatcher(0, b"__name__", b"rr"),
                   protowire.PromMatcher(0, b"i", b"1")])]
            )
            r = urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{port}/api/v1/prom/remote/read",
                data=snappy.compress(req), method="POST",
                headers={"Content-Type": "application/x-protobuf"},
            ))
            results = protowire.decode_read_response(snappy.decompress(r.read()))
            assert len(results) == 1 and len(results[0]) == 1
            (ts,) = results[0]
            assert (b"i", b"1") in ts.labels
            assert ts.samples == [(START // 10**6 + 1000, 1.0)]
        finally:
            api.shutdown()

    def test_http_limits_plumbed(self, db):
        import json as _json
        import urllib.error
        import urllib.request

        from m3_tpu.query.api import CoordinatorAPI
        from m3_tpu.query.engine import QueryLimits

        for i in range(8):
            write_series(db, b"h", [(b"i", str(i).encode())], [(START + 10**9, 1.0)])
        api = CoordinatorAPI(db, limits=QueryLimits(max_series=3))
        port = api.serve(port=0)
        try:
            url = (f"http://127.0.0.1:{port}/api/v1/query_range?query=h"
                   f"&start={START//10**9 + 60}&end={START//10**9 + 60}&step=60")
            try:
                urllib.request.urlopen(url)
                raise AssertionError("expected 400")
            except urllib.error.HTTPError as e:
                body = _json.loads(e.read())
                assert "limit" in body["error"]
        finally:
            api.shutdown()


class TestSubqueriesAndAt:
    def test_parse_subquery_forms(self):
        from m3_tpu.query.promql import SubqueryExpr, parse

        e = parse("rate(m[5m])[30m:5m]")
        assert isinstance(e, SubqueryExpr)
        assert e.range_ns == 30 * MIN * 10**9 // 10**9 * 10**9 or e.range_ns == 1800 * 10**9
        assert e.step_ns == 300 * 10**9
        e = parse("m[10m:]")
        assert isinstance(e, SubqueryExpr) and e.step_ns is None
        e = parse("m[10m: 30s]")
        assert e.step_ns == 30 * 10**9
        e = parse("max_over_time(rate(m[1m])[10m:1m] offset 5m)")
        sq = e.args[0]
        assert isinstance(sq, SubqueryExpr) and sq.offset_ns == 300 * 10**9

    def test_parse_at_modifier(self):
        from m3_tpu.query.promql import parse

        e = parse("m @ 1600000000")
        assert e.at_ns == 1_600_000_000 * 10**9
        e = parse("m @ start()")
        assert e.at_ns == "start"
        e = parse("rate(m[5m] @ end())")
        assert e.args[0].selector.at_ns == "end"

    def test_subquery_max_of_rate(self, db):
        """max_over_time(rate(ctr[2m])[20m:1m]): the classic pattern."""
        # counter rising 1/s for 10m then 3/s for 10m
        pts = []
        v = 0.0
        for j in range(121):
            t = START + j * 10 * 10**9
            pts.append((t, v))
            v += 10.0 if j < 60 else 30.0
        write_series(db, b"ctr", [(b"k", b"v")], pts)
        eng = Engine(db)
        end = START + 1200 * 10**9
        res, _ = eng.query_range("max_over_time(rate(ctr[2m])[20m:1m])",
                                 end, end, MIN)
        # max rate over the window is the late-phase 3/s
        assert abs(res.values[0, 0] - 3.0) < 1e-9

    def test_subquery_avg_matches_direct(self, db):
        """avg_over_time(m[10m:1m]) where m is 1-min-sampled equals the
        plain average of those samples."""
        pts = [(START + j * 60 * 10**9, float(j)) for j in range(11)]
        write_series(db, b"g", [(b"k", b"v")], pts)
        eng = Engine(db)
        end = START + 600 * 10**9
        res, _ = eng.query_instant("avg_over_time(g[10m:1m])", end)
        # aligned instants in (end-10m, end]: minutes 1..10 -> values 1..10
        assert abs(res.values[0, 0] - 5.5) < 1e-9

    def test_at_pins_evaluation_time(self, db):
        pts = [(START + j * 60 * 10**9, float(j)) for j in range(11)]
        write_series(db, b"p", [(b"k", b"v")], pts)
        eng = Engine(db)
        at_s = (START + 300 * 10**9) // 10**9
        res, _ = eng.query_range(f"p @ {at_s}", START + 60 * 10**9,
                                 START + 600 * 10**9, MIN)
        # every step returns the value at the pinned instant (j=5)
        vals = res.values[0]
        assert np.allclose(vals, 5.0)

    def test_at_start_end(self, db):
        pts = [(START + j * 60 * 10**9, float(j)) for j in range(11)]
        write_series(db, b"q", [(b"k", b"v")], pts)
        eng = Engine(db)
        res, _ = eng.query_range("q @ end()", START + 60 * 10**9,
                                 START + 600 * 10**9, MIN)
        assert np.allclose(res.values[0], 10.0)
        res, _ = eng.query_range("q @ start()", START + 60 * 10**9,
                                 START + 600 * 10**9, MIN)
        assert np.allclose(res.values[0], 1.0)


# 2021-03-14 15:09:26 UTC (a Sunday, day 73 of the year)
DT_T0_NS = 1615734566 * 10**9


class TestDatetimeFunctions:
    """Upstream date/time extractors (functions.go dateWrapper family)."""

    @pytest.fixture(scope="class")
    def eng(self, tmp_path_factory):
        from m3_tpu.storage.database import Database
        from m3_tpu.storage.options import DatabaseOptions

        db = Database(str(tmp_path_factory.mktemp("dt")),
                      DatabaseOptions(n_shards=1))
        db.create_namespace("default")
        db.write_tagged("default", b"ts", [(b"k", b"v")],
                        DT_T0_NS, float(DT_T0_NS // 10**9))
        from m3_tpu.query.engine import Engine

        return Engine(db, "default")

    @pytest.mark.parametrize("q,want", [
        ("year(ts)", 2021), ("month(ts)", 3), ("day_of_month(ts)", 14),
        ("day_of_week(ts)", 0), ("day_of_year(ts)", 73),
        ("days_in_month(ts)", 31), ("hour(ts)", 15), ("minute(ts)", 9),
    ])
    def test_components(self, eng, q, want):
        v, _ = eng.query_range(q, DT_T0_NS, DT_T0_NS, 60 * 10**9)
        assert float(v.values[0][0]) == want

    def test_no_arg_uses_eval_time(self, eng):
        v, _ = eng.query_range("hour()", DT_T0_NS, DT_T0_NS, 60 * 10**9)
        assert v.labels == [{}] and float(v.values[0][0]) == 15.0

    def test_pi_and_inverse_hyperbolics(self, eng):
        import math

        v, _ = eng.query_range("pi() * sgn(ts)", DT_T0_NS, DT_T0_NS, 60 * 10**9)
        assert float(v.values[0][0]) == pytest.approx(math.pi)
        v, _ = eng.query_range("atanh(sgn(ts) * 0.5)", DT_T0_NS, DT_T0_NS, 60 * 10**9)
        assert float(v.values[0][0]) == pytest.approx(math.atanh(0.5))

    def test_scalar_argument_rejected(self, eng):
        from m3_tpu.query.engine import EvalError

        with pytest.raises(EvalError):
            eng.query_range("year(2)", DT_T0_NS, DT_T0_NS, 60 * 10**9)
