"""Acked pub/sub transport tests: delivery, ack, redelivery, backpressure."""

import threading
import time

import pytest

from m3_tpu.msg.consumer import Consumer
from m3_tpu.msg.producer import Producer


def wait_until(cond, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


class TestPubSub:
    def test_delivery_and_ack(self):
        got = []
        consumer = Consumer(lambda shard, payload: got.append((shard, payload)))
        producer = Producer(("127.0.0.1", consumer.port), retry_after_s=0.5)
        try:
            for i in range(20):
                producer.publish(i % 4, f"m{i}".encode())
            assert wait_until(lambda: len(got) == 20)
            assert wait_until(lambda: producer.unacked == 0)
            assert {p for _, p in got} == {f"m{i}".encode() for i in range(20)}
            assert {s for s, _ in got} == {0, 1, 2, 3}
        finally:
            producer.close()
            consumer.close()

    def test_redelivery_on_handler_failure(self):
        calls = {"n": 0}
        delivered = threading.Event()

        def flaky(shard, payload):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("transient")
            delivered.set()

        consumer = Consumer(flaky)
        producer = Producer(("127.0.0.1", consumer.port), retry_after_s=0.3)
        try:
            producer.publish(0, b"retry-me")
            assert delivered.wait(10)
            assert calls["n"] >= 2  # first failed, redelivered
            assert wait_until(lambda: producer.unacked == 0)
        finally:
            producer.close()
            consumer.close()

    def test_consumer_down_then_up(self):
        got = []
        consumer = Consumer(lambda s, p: got.append(p))
        port = consumer.port
        consumer.close()
        producer = Producer(("127.0.0.1", port), retry_after_s=0.3)
        try:
            producer.publish(0, b"early")
            time.sleep(0.3)  # producer retrying against a dead endpoint
            consumer2 = Consumer(lambda s, p: got.append(p), port=port)
            assert wait_until(lambda: got == [b"early"])
            assert wait_until(lambda: producer.unacked == 0)
            consumer2.close()
        finally:
            producer.close()

    def test_backpressure_drops_oldest(self):
        # no consumer: buffer fills, the oldest messages get dropped
        dropped = []
        producer = Producer(("127.0.0.1", 1), max_buffer=5,
                            retry_after_s=60, on_drop=lambda p: dropped.append(p.payload))
        try:
            for i in range(8):
                producer.publish(0, f"x{i}".encode())
            assert producer.num_dropped == 3
            assert dropped == [b"x0", b"x1", b"x2"]
        finally:
            producer.close()
