"""Acked pub/sub transport tests: delivery, ack, redelivery, backpressure."""

import threading
import time

import pytest

from m3_tpu.msg.consumer import Consumer
from m3_tpu.msg.producer import Producer


def wait_until(cond, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


class TestPubSub:
    def test_delivery_and_ack(self):
        got = []
        consumer = Consumer(lambda shard, payload: got.append((shard, payload)))
        producer = Producer(("127.0.0.1", consumer.port), retry_after_s=0.5)
        try:
            for i in range(20):
                producer.publish(i % 4, f"m{i}".encode())
            assert wait_until(lambda: len(got) == 20)
            assert wait_until(lambda: producer.unacked == 0)
            assert {p for _, p in got} == {f"m{i}".encode() for i in range(20)}
            assert {s for s, _ in got} == {0, 1, 2, 3}
        finally:
            producer.close()
            consumer.close()

    def test_redelivery_on_handler_failure(self):
        calls = {"n": 0}
        delivered = threading.Event()

        def flaky(shard, payload):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("transient")
            delivered.set()

        consumer = Consumer(flaky)
        producer = Producer(("127.0.0.1", consumer.port), retry_after_s=0.3)
        try:
            producer.publish(0, b"retry-me")
            assert delivered.wait(10)
            assert calls["n"] >= 2  # first failed, redelivered
            assert wait_until(lambda: producer.unacked == 0)
        finally:
            producer.close()
            consumer.close()

    def test_consumer_down_then_up(self):
        got = []
        consumer = Consumer(lambda s, p: got.append(p))
        port = consumer.port
        consumer.close()
        producer = Producer(("127.0.0.1", port), retry_after_s=0.3)
        try:
            producer.publish(0, b"early")
            time.sleep(0.3)  # producer retrying against a dead endpoint
            consumer2 = Consumer(lambda s, p: got.append(p), port=port)
            assert wait_until(lambda: got == [b"early"])
            assert wait_until(lambda: producer.unacked == 0)
            consumer2.close()
        finally:
            producer.close()

    def test_backpressure_drops_oldest(self):
        # no consumer: buffer fills, the oldest messages get dropped
        dropped = []
        producer = Producer(("127.0.0.1", 1), max_buffer=5,
                            retry_after_s=60, on_drop=lambda p: dropped.append(p.payload))
        try:
            for i in range(8):
                producer.publish(0, f"x{i}".encode())
            assert producer.num_dropped == 3
            assert dropped == [b"x0", b"x1", b"x2"]
        finally:
            producer.close()


class TestRequeueDedupe:
    """Regression (ISSUE 2 satellite): a message must never be queued
    twice — the writer's send-error requeue and the stale scan used to be
    able to both enqueue the same id on a flappy link, double-sending it."""

    def _idle_producer(self):
        # port 1 never accepts: the writer thread loops in _connect and
        # leaves the queue alone, so requeue paths can be driven directly
        return Producer(("127.0.0.1", 1), retry_after_s=0.2)

    def test_error_requeue_after_stale_scan_does_not_duplicate(self):
        producer = self._idle_producer()
        try:
            msg_id = producer.publish(0, b"flappy")
            with producer._cv:
                # the writer popped it and is mid-send...
                producer._queue.remove(msg_id)
                producer._queued.discard(msg_id)
                p = producer._pending[msg_id]
                p.sent_at = time.monotonic() - 10  # long overdue
                # ...the stale scan re-appends it...
                producer._last_requeue_scan = 0.0
                producer._requeue_stale_locked()
                assert producer._queue.count(msg_id) == 1
            # ...and THEN the in-flight send fails: must not enqueue again
            producer._requeue_after_error(msg_id)
            with producer._lock:
                assert producer._queue.count(msg_id) == 1
                assert producer._queued == set(producer._queue)
        finally:
            producer.close()

    def test_stale_scan_skips_already_queued_and_acked(self):
        producer = self._idle_producer()
        try:
            a = producer.publish(0, b"a")  # still queued
            b = producer.publish(1, b"b")
            with producer._cv:
                # b was sent and acked mid-flight
                producer._queue.remove(b)
                producer._queued.discard(b)
                del producer._pending[b]
                producer._pending[a].sent_at = time.monotonic() - 10
                producer._last_requeue_scan = 0.0
                producer._requeue_stale_locked()
                assert producer._queue.count(a) == 1  # queued: not doubled
                assert b not in producer._queue      # acked: not revived
            producer._requeue_after_error(b)  # late failure of acked msg
            with producer._lock:
                assert b not in producer._queue
        finally:
            producer.close()

    def test_no_double_send_under_injected_socket_faults(self):
        """End-to-end: a flappy link (injected send faults) redelivers but
        the queue invariant (no duplicate ids) holds throughout, and every
        message lands."""
        from m3_tpu.utils import faults

        got = []
        consumer = Consumer(lambda s, p: got.append(p), ack_batch=1)
        faults.configure("msg.producer.send=error:p0.3:x6", seed=13)
        try:
            producer = Producer(("127.0.0.1", consumer.port),
                                retry_after_s=0.2)
            for i in range(30):
                producer.publish(0, b"m%d" % i)
            deadline = time.monotonic() + 10
            while producer.unacked and time.monotonic() < deadline:
                with producer._lock:
                    assert len(producer._queue) == len(set(producer._queue))
                    assert set(producer._queue) == producer._queued
                time.sleep(0.01)
            assert producer.unacked == 0
            assert set(got) == {b"m%d" % i for i in range(30)}
        finally:
            faults.disable()
            producer.close()
            consumer.close()
