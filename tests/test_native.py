"""Native C++ codec tests: bit-exactness vs the Python scalar codec."""

import numpy as np
import pytest

from m3_tpu.encoding.m3tsz import Encoder, native
from m3_tpu.encoding.m3tsz import decode as py_decode
from m3_tpu.utils.xtime import TimeUnit

START = 1_599_998_400_000_000_000

pytestmark = pytest.mark.skipif(
    not native.available(), reason="no C++ toolchain for native codec"
)


def series(rng, n=150, unit_step=10**9, scale=60):
    times = START + np.cumsum(rng.integers(1, scale, n)) * unit_step
    return times.astype(np.int64), rng.normal(100, 25, n)


class TestNativeCodec:
    def test_bit_exact_vs_python(self, rng):
        times, values = series(rng)
        stream = native.encode_series(times, values, START, TimeUnit.SECOND)
        enc = Encoder(START, int_optimized=False)
        for t, v in zip(times, values):
            enc.encode(int(t), float(v), TimeUnit.SECOND)
        assert stream == enc.stream()

    def test_roundtrip(self, rng):
        times, values = series(rng)
        stream = native.encode_series(times, values, START, TimeUnit.SECOND)
        dt, dv = native.decode_series(stream, TimeUnit.SECOND)
        np.testing.assert_array_equal(dt, times)
        np.testing.assert_array_equal(dv, values)

    def test_cross_decoding(self, rng):
        times, values = series(rng)
        stream = native.encode_series(times, values, START, TimeUnit.SECOND)
        dps = py_decode(stream, int_optimized=False)
        assert [d.value for d in dps] == list(values)
        enc = Encoder(START, int_optimized=False)
        for t, v in zip(times, values):
            enc.encode(int(t), float(v), TimeUnit.SECOND)
        dt, dv = native.decode_series(enc.stream(), TimeUnit.SECOND)
        np.testing.assert_array_equal(dt, times)

    def test_nanosecond_unit(self, rng):
        times, values = series(rng, unit_step=1, scale=10**10)
        stream = native.encode_series(times, values, START, TimeUnit.NANOSECOND)
        enc = Encoder(START, int_optimized=False,
                      default_time_unit=TimeUnit.NANOSECOND)
        for t, v in zip(times, values):
            enc.encode(int(t), float(v), TimeUnit.NANOSECOND)
        assert stream == enc.stream()
        dt, dv = native.decode_series(stream, TimeUnit.NANOSECOND)
        np.testing.assert_array_equal(dv, values)

    def test_errors(self, rng):
        times, values = series(rng, n=5)
        with pytest.raises(ValueError, match="misaligned|overflow"):
            native.encode_series(times, values, START + 1, TimeUnit.SECOND)
        bad_times = times.copy(); bad_times[2] = 0
        with pytest.raises(OverflowError):
            native.encode_series(bad_times, values, START, TimeUnit.SECOND)
        with pytest.raises(ValueError):
            # a stream with an annotation marker is a host-path feature the
            # native float-mode decoder must reject, not misparse
            enc = Encoder(START, int_optimized=False)
            enc.encode(START + 10**9, 1.0, TimeUnit.SECOND, b"annotation")
            enc.encode(START + 2 * 10**9, 1.0, TimeUnit.SECOND)
            native.decode_series(enc.stream(), TimeUnit.SECOND)

    def test_special_values(self):
        times = START + (np.arange(8) + 1) * 10**9
        values = np.array([0.0, -0.0, np.inf, -np.inf, np.nan, 1e300, 1e-300, 7.0])
        stream = native.encode_series(times, values, START, TimeUnit.SECOND)
        dt, dv = native.decode_series(stream, TimeUnit.SECOND)
        for a, b in zip(dv, values):
            assert a == b or (np.isnan(a) and np.isnan(b))
