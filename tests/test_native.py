"""Native C++ codec tests: bit-exactness vs the Python scalar codec."""

import numpy as np
import pytest

from m3_tpu.encoding.m3tsz import Encoder, native
from m3_tpu.encoding.m3tsz import decode as py_decode
from m3_tpu.utils.xtime import TimeUnit

START = 1_599_998_400_000_000_000

pytestmark = pytest.mark.skipif(
    not native.available(), reason="no C++ toolchain for native codec"
)


def series(rng, n=150, unit_step=10**9, scale=60):
    times = START + np.cumsum(rng.integers(1, scale, n)) * unit_step
    return times.astype(np.int64), rng.normal(100, 25, n)


class TestNativeCodec:
    def test_bit_exact_vs_python(self, rng):
        times, values = series(rng)
        stream = native.encode_series(times, values, START, TimeUnit.SECOND)
        enc = Encoder(START, int_optimized=False)
        for t, v in zip(times, values):
            enc.encode(int(t), float(v), TimeUnit.SECOND)
        assert stream == enc.stream()

    def test_roundtrip(self, rng):
        times, values = series(rng)
        stream = native.encode_series(times, values, START, TimeUnit.SECOND)
        dt, dv = native.decode_series(stream, TimeUnit.SECOND)
        np.testing.assert_array_equal(dt, times)
        np.testing.assert_array_equal(dv, values)

    def test_cross_decoding(self, rng):
        times, values = series(rng)
        stream = native.encode_series(times, values, START, TimeUnit.SECOND)
        dps = py_decode(stream, int_optimized=False)
        assert [d.value for d in dps] == list(values)
        enc = Encoder(START, int_optimized=False)
        for t, v in zip(times, values):
            enc.encode(int(t), float(v), TimeUnit.SECOND)
        dt, dv = native.decode_series(enc.stream(), TimeUnit.SECOND)
        np.testing.assert_array_equal(dt, times)

    def test_nanosecond_unit(self, rng):
        times, values = series(rng, unit_step=1, scale=10**10)
        stream = native.encode_series(times, values, START, TimeUnit.NANOSECOND)
        enc = Encoder(START, int_optimized=False,
                      default_time_unit=TimeUnit.NANOSECOND)
        for t, v in zip(times, values):
            enc.encode(int(t), float(v), TimeUnit.NANOSECOND)
        assert stream == enc.stream()
        dt, dv = native.decode_series(stream, TimeUnit.NANOSECOND)
        np.testing.assert_array_equal(dv, values)

    def test_errors(self, rng):
        times, values = series(rng, n=5)
        with pytest.raises(ValueError, match="misaligned|overflow"):
            native.encode_series(times, values, START + 1, TimeUnit.SECOND)
        bad_times = times.copy(); bad_times[2] = 0
        with pytest.raises(OverflowError):
            native.encode_series(bad_times, values, START, TimeUnit.SECOND)
        with pytest.raises(ValueError):
            # a stream with an annotation marker is a host-path feature the
            # native float-mode decoder must reject, not misparse
            enc = Encoder(START, int_optimized=False)
            enc.encode(START + 10**9, 1.0, TimeUnit.SECOND, b"annotation")
            enc.encode(START + 2 * 10**9, 1.0, TimeUnit.SECOND)
            native.decode_series(enc.stream(), TimeUnit.SECOND)

    def test_special_values(self):
        times = START + (np.arange(8) + 1) * 10**9
        values = np.array([0.0, -0.0, np.inf, -np.inf, np.nan, 1e300, 1e-300, 7.0])
        stream = native.encode_series(times, values, START, TimeUnit.SECOND)
        dt, dv = native.decode_series(stream, TimeUnit.SECOND)
        for a, b in zip(dv, values):
            assert a == b or (np.isnan(a) and np.isnan(b))


class TestNativeBatchCodec:
    """The v2 serving-path codec (word-level bit I/O, threaded batch)."""

    def test_batch_bit_identical_to_v1(self, rng):
        B, T = 64, 150
        times = np.stack([series(rng, n=T)[0] for _ in range(B)])
        values = np.stack([series(rng, n=T)[1] for _ in range(B)])
        streams = native.encode_batch(times, values, np.full(B, START),
                                      TimeUnit.SECOND)
        for b in range(0, B, 7):
            v1 = native.encode_series(times[b], values[b], START,
                                      TimeUnit.SECOND)
            assert streams[b] == v1

    def test_batch_roundtrip_threaded(self, rng):
        B, T = 32, 100
        times = np.stack([series(rng, n=T)[0] for _ in range(B)])
        values = np.stack([series(rng, n=T)[1] for _ in range(B)])
        streams = native.encode_batch(times, values, np.full(B, START),
                                      TimeUnit.SECOND, threads=4)
        dt, dv, ns = native.decode_batch(streams, TimeUnit.SECOND,
                                         max_points=T, threads=4)
        assert (ns == T).all()
        np.testing.assert_array_equal(dt[:, :T], times)
        np.testing.assert_array_equal(dv[:, :T].view(np.float64), values)

    def test_batch_n_points(self, rng):
        B, T = 8, 50
        times = np.stack([series(rng, n=T)[0] for _ in range(B)])
        values = np.stack([series(rng, n=T)[1] for _ in range(B)])
        n_points = np.array([T, 0, 10, T, 1, 25, T, 3], np.int32)
        streams = native.encode_batch(times, values, np.full(B, START),
                                      TimeUnit.SECOND, n_points=n_points)
        dt, dv, ns = native.decode_batch(streams, TimeUnit.SECOND,
                                         max_points=T)
        np.testing.assert_array_equal(ns, n_points)
        for b in range(B):
            n = n_points[b]
            np.testing.assert_array_equal(dt[b, :n], times[b, :n])

    def test_batch_special_values_and_repeats(self):
        T = 16
        times = START + (np.arange(T) + 1) * 10**9
        vals = np.array([1.5, 1.5, 1.5, 0.0, -0.0, np.inf, -np.inf, np.nan,
                         np.nan, 1e300, 1e-300, 7.0, 7.0, 7.0, -1.25, 2.5])
        streams = native.encode_batch(times[None, :], vals[None, :],
                                      np.array([START]), TimeUnit.SECOND)
        v1 = native.encode_series(times, vals, START, TimeUnit.SECOND)
        assert streams[0] == v1
        dt, dv, ns = native.decode_batch(streams, TimeUnit.SECOND,
                                         max_points=T)
        assert ns[0] == T
        got = dv[0, :T].view(np.float64)
        for a, b in zip(got, vals):
            assert a == b or (np.isnan(a) and np.isnan(b))

    def test_roundtrip_batch_bench(self, rng):
        B, T = 128, 60
        times = np.stack([series(rng, n=T)[0] for _ in range(B)])
        values = np.stack([series(rng, n=T)[1] for _ in range(B)])
        rate, lt, lv = native.bench_roundtrip_batch(
            times, values, START, TimeUnit.SECOND, threads=2)
        assert rate > 0
        np.testing.assert_array_equal(lt, times[-1])
        np.testing.assert_array_equal(lv.view(np.float64), values[-1])


class TestHostpathDispatch:
    def test_encode_blocks_native_on_cpu(self, rng, monkeypatch):
        from m3_tpu.encoding.m3tsz import hostpath
        from m3_tpu.utils import dispatch

        monkeypatch.delenv("M3_TPU_DEVICE_OPS", raising=False)
        B, T = 4, 30
        times = np.stack([series(rng, n=T)[0] for _ in range(B)])
        values = np.stack([series(rng, n=T)[1] for _ in range(B)])
        before = dispatch.counters["m3tsz_encode_native"]
        streams = hostpath.encode_blocks(
            times, values.view(np.uint64), np.full(B, START),
            np.full(B, T, np.int32), TimeUnit.SECOND, False)
        assert dispatch.counters["m3tsz_encode_native"] == before + 1
        for b in range(B):
            t, v = hostpath.decode_stream(streams[b], TimeUnit.SECOND, False)
            np.testing.assert_array_equal(t, times[b])
