"""Whole-query compilation (query/compiler.py, ROADMAP #2).

The contract under test: for every COVERED plan shape the compiled path
returns element-identical results to the op-by-op interpreter (NaN masks
exactly equal, values within the documented 1e-9 relative envelope for
XLA reassociation — most shapes are bit-exact), uncovered shapes fall
back transparently with a counted tracepoint, repeated identical-shape
queries pay exactly ONE trace+compile, and the plan-shape cache stays
bounded.
"""

import os

import numpy as np
import pytest

from m3_tpu.query import compiler, explain, promql
from m3_tpu.query.engine import Engine, Vector
from m3_tpu.query.windows import NS, RaggedSeries
from m3_tpu.storage.database import Database
from m3_tpu.storage.options import DatabaseOptions
from m3_tpu.utils import dispatch

MIN = 60 * NS
START = 1_599_998_400_000_000_000


@pytest.fixture(scope="module")
def db(tmp_path_factory):
    db = Database(str(tmp_path_factory.mktemp("qc") / "db"),
                  DatabaseOptions(n_shards=4))
    db.create_namespace("default")
    db.open(START)
    rng = np.random.default_rng(42)
    hosts = [b"h%02d" % i for i in range(7)]
    jobs = [b"api", b"web", b"batch"]
    for i in range(60):
        tags = [(b"host", hosts[i % len(hosts)]), (b"job", jobs[i % len(jobs)])]
        # irregular sample spacing + counter resets + a few gaps, so the
        # sweep hits empty windows, reset adjustment and extrapolation
        t = START
        acc = float(rng.integers(0, 50))
        for _ in range(40):
            t += int(rng.integers(5, 40)) * NS
            if rng.random() < 0.06:
                acc = 0.0  # counter reset
            acc += float(rng.integers(0, 9))
            if rng.random() < 0.9:
                db.write_tagged("default", b"reqs", tags, t, acc)
    yield db
    db.close()


@pytest.fixture
def engine(db):
    return Engine(db, resolve_tiers=False)


def run_both(engine, monkeypatch, q, start, end, step):
    monkeypatch.setenv("M3_TPU_QUERY_COMPILE", "0")
    vi, _ = engine.query_range(q, start, end, step)
    monkeypatch.setenv("M3_TPU_QUERY_COMPILE", "1")
    vc, _ = engine.query_range(q, start, end, step)
    return vi, vc


def assert_parity(vi: Vector, vc: Vector, q: str):
    assert type(vi) is type(vc), q
    assert vi.labels == vc.labels, q
    assert vi.values.shape == vc.values.shape, q
    assert np.array_equal(np.isnan(vi.values), np.isnan(vc.values)), q
    assert np.allclose(vi.values, vc.values, rtol=1e-9, atol=0,
                       equal_nan=True), q


class TestCoverageMatrix:
    COVERED = [
        "reqs",
        "rate(reqs[5m])",
        "increase(reqs[3m])",
        "delta(reqs[4m] offset 2m)",
        "irate(reqs[5m])",
        "idelta(reqs[5m])",
        "avg_over_time(reqs[4m])",
        "sum_over_time(reqs[2m])",
        "count_over_time(reqs[3m])",
        "present_over_time(reqs[3m])",
        "max_over_time(reqs[5m])",
        "min_over_time(reqs[3m])",
        "sum by (job) (max_over_time(reqs[4m]))",
        "sum by (host) (rate(reqs[5m]))",
        "quantile by (job) (0.9, rate(reqs[5m]))",
        "max without (host) (delta(reqs[5m]))",
        "rate(reqs[5m]) * 8 / 1024",
        "2 - sum(rate(reqs[5m]))",
        "min by (job) (irate(reqs[5m]) ^ 2)",
    ]
    UNCOVERED = [
        "topk(3, rate(reqs[5m]))",                    # uncovered aggregator
        "stddev by (host) (rate(reqs[5m]))",          # uncovered aggregator
        "rate(reqs[5m]) > 0.5",                       # comparison semantics
        "last_over_time(reqs[5m])",                   # uncovered window fn
        "holt_winters(reqs[5m], 0.5, 0.5)",           # uncovered function
        "sum by (host) (sum by (job) (reqs))",        # two aggregations
        "quantile by (job) (scalar(reqs), reqs)",     # non-literal phi
        "avg_over_time(reqs[5m:1m])",                 # subquery range arg
        "-rate(reqs[5m])",                            # unary
        "abs(rate(reqs[5m]))",                        # math function
    ]

    def test_covered_shapes_match(self):
        for q in self.COVERED:
            assert compiler.match(promql.parse(q)) is not None, q

    def test_uncovered_shapes_fall_back(self):
        for q in self.UNCOVERED:
            assert compiler.match(promql.parse(q)) is None, q

    def test_signature_separates_program_from_data(self):
        # scalars, grouping labels and phi are data, not program identity
        a = compiler.match(promql.parse("sum by (host) (rate(reqs[5m]) * 8)"))
        b = compiler.match(promql.parse("sum by (job) (rate(reqs[1m]) * 99)"))
        assert a.sig == b.sig
        c = compiler.match(promql.parse("avg by (host) (rate(reqs[5m]) * 8)"))
        assert c.sig != a.sig


class TestParitySweep:
    """Seeded property sweep: random covered plans over the shared
    fixture data must be element-identical (or within the documented
    envelope) between the compiled program and the interpreter."""

    BASES = ["rate(reqs[{r}]{o})", "increase(reqs[{r}]{o})",
             "delta(reqs[{r}]{o})", "irate(reqs[{r}]{o})",
             "idelta(reqs[{r}]{o})", "avg_over_time(reqs[{r}]{o})",
             "sum_over_time(reqs[{r}]{o})", "count_over_time(reqs[{r}]{o})",
             "present_over_time(reqs[{r}]{o})", "min_over_time(reqs[{r}]{o})",
             "max_over_time(reqs[{r}]{o})", "reqs{o_instant}"]
    AGGS = ["sum", "avg", "min", "max", "count", "quantile"]
    BIN_OPS = ["+", "-", "*", "/", "%", "^"]
    SCALARS = [2, 0.5, 3.7, -1.5, 60]
    PHIS = [0.5, 0.9, 0.99, 0.0, 1.0, -0.5, 1.5]

    def random_plan(self, rng) -> str:
        base = str(rng.choice(self.BASES))
        off = " offset 1m" if rng.random() < 0.3 else ""
        expr = base.format(r=f"{rng.integers(1, 7)}m", o=off,
                           o_instant=off)
        def add_bin(e):
            op = str(rng.choice(self.BIN_OPS))
            c = rng.choice(self.SCALARS)
            return f"({e}) {op} {c}" if rng.random() < 0.5 \
                else f"{c} {op} ({e})"
        if rng.random() < 0.4:
            expr = add_bin(expr)
        if rng.random() < 0.75:
            op = str(rng.choice(self.AGGS))
            by = str(rng.choice(["by (host)", "by (job)",
                                 "by (host, job)", "without (host)", ""]))
            if op == "quantile":
                phi = rng.choice(self.PHIS)
                expr = f"quantile {by} ({phi}, {expr})"
            else:
                expr = f"{op} {by} ({expr})"
        if rng.random() < 0.4:
            expr = add_bin(expr)
        return expr

    def test_sweep(self, engine, monkeypatch):
        rng = np.random.default_rng(1234)
        compiled_runs = 0
        for i in range(14):
            q = self.random_plan(rng)
            start = START + int(rng.integers(0, 5)) * MIN
            step = int(rng.integers(1, 4)) * 30 * NS
            end = START + int(rng.integers(10, 25)) * MIN
            before = dispatch.counters["query.compile[compiled]"]
            vi, vc = run_both(engine, monkeypatch, q, start, end, step)
            assert dispatch.counters["query.compile[compiled]"] == \
                before + 1, f"plan not compiled: {q}"
            compiled_runs += 1
            assert_parity(vi, vc, q)
        assert compiled_runs == 14

    def test_empty_match_parity(self, engine, monkeypatch):
        for q in ("sum by (host) (rate(nope[5m]))", "rate(nope[5m])",
                  "nope"):
            vi, vc = run_both(engine, monkeypatch, q, START, START + 10 * MIN,
                              MIN)
            assert vi.labels == vc.labels == []
            assert vi.values.shape == vc.values.shape

    def test_power_cannot_resurrect_dead_series(self, tmp_path,
                                                monkeypatch):
        """The interpreter _compacts (drops all-NaN series) between
        stages; elementwise NaN ** 0 == 1 ** NaN == 1.0 would resurrect
        a dead row in the fused program, so the ^ stage masks rows that
        were dead before it — parity holds on the series SET too."""
        db = Database(str(tmp_path / "db"), DatabaseOptions(n_shards=1))
        db.create_namespace("default")
        db.open(START)
        # host=a: one sample only -> irate is NaN at every step (dead)
        db.write_tagged("default", b"m", [(b"host", b"a")],
                        START + 30 * NS, 5.0)
        for k in range(1, 30):
            db.write_tagged("default", b"m", [(b"host", b"b")],
                            START + k * 20 * NS, float(k))
        eng = Engine(db, resolve_tiers=False)
        try:
            for q in ("irate(m[5m]) ^ 0",
                      "1 ^ irate(m[5m])",
                      "(irate(m[5m]) * 2) ^ 0",
                      "sum by (host) (irate(m[5m]) ^ 0)"):
                vi, vc = run_both(eng, monkeypatch, q, START,
                                  START + 10 * MIN, MIN)
                assert_parity(vi, vc, q)
            monkeypatch.setenv("M3_TPU_QUERY_COMPILE", "1")
            vc, _ = eng.query_range("irate(m[5m]) ^ 0", START,
                                    START + 10 * MIN, MIN)
            assert [lb.get(b"host") for lb in vc.labels] == [b"b"]
        finally:
            db.close()

    def test_instant_query_parity(self, engine, monkeypatch):
        monkeypatch.setenv("M3_TPU_QUERY_COMPILE", "0")
        vi, _ = engine.query_instant("sum by (job) (rate(reqs[5m]))",
                                     START + 10 * MIN)
        monkeypatch.setenv("M3_TPU_QUERY_COMPILE", "1")
        vc, _ = engine.query_instant("sum by (job) (rate(reqs[5m]))",
                                     START + 10 * MIN)
        assert_parity(vi, vc, "instant")


class TestVectorVectorBinop:
    """Vector-vector binops on matching label sets (the carried PR-10
    follow-up): both sides compile into their own fused programs and the
    combine replicates the interpreter's one-to-one default matching —
    parity holds on labels, NaN masks and values, and on the ERRORS the
    matching machinery raises."""

    COVERED = [
        "rate(reqs[5m]) + rate(reqs[5m])",
        "irate(reqs[5m]) / avg_over_time(reqs[3m])",
        "sum by (job) (irate(reqs[5m])) / sum by (job) "
        "(count_over_time(reqs[5m]))",
        "max_over_time(reqs[4m]) - min_over_time(reqs[4m])",
        "(rate(reqs[5m]) * 8) % (delta(reqs[3m]) + 2)",
        "reqs ^ present_over_time(reqs[2m])",
        "sum by (host, job) (reqs) * sum by (host, job) (reqs offset 1m)",
    ]
    UNCOVERED = [
        "rate(reqs[5m]) > rate(reqs[3m])",            # comparison
        "reqs + on (job) reqs",                       # explicit on()
        "reqs + ignoring (host) reqs",                # explicit ignoring()
        "sum by (job) (reqs) + bool sum by (job) (reqs)",  # bool mode
        "reqs and reqs",                              # set operator
        "topk(2, reqs) + reqs",                       # uncovered side
    ]

    def test_covered_shapes_match(self):
        for q in self.COVERED:
            assert compiler.match_vecbin(promql.parse(q)) is not None, q
            # the single-chain matcher stays blind to these (its sig
            # space is one selector); the vecbin matcher owns them
            assert compiler.match(promql.parse(q)) is None, q

    def test_uncovered_shapes_fall_back(self):
        for q in self.UNCOVERED:
            assert compiler.match_vecbin(promql.parse(q)) is None, q
            assert compiler.match(promql.parse(q)) is None, q

    def test_parity(self, engine, monkeypatch):
        for q in self.COVERED:
            before = dispatch.counters["query.compile[compiled]"]
            vi, vc = run_both(engine, monkeypatch, q, START,
                              START + 14 * MIN, MIN)
            assert dispatch.counters["query.compile[compiled]"] == \
                before + 1, f"plan not compiled: {q}"
            assert_parity(vi, vc, q)

    def test_partial_label_match_drops_unmatched(self, engine, monkeypatch):
        # per-host aggregate vs per-(host,job) series: match keys differ
        # per series; only exact label-set matches combine — and the
        # interpreter agrees on WHICH rows survive
        q = ("sum by (host) (rate(reqs[5m])) "
             "+ sum by (host) (irate(reqs[4m]))")
        vi, vc = run_both(engine, monkeypatch, q, START, START + 10 * MIN,
                          MIN)
        assert_parity(vi, vc, q)

    def test_empty_key_intersection_parity(self, engine, monkeypatch):
        # per-job keys vs the unlabeled sum(): no key matches — both
        # paths agree the result is EMPTY, not an error
        q = "sum by (job) (reqs) * sum(reqs)"
        vi, vc = run_both(engine, monkeypatch, q, START, START + 10 * MIN,
                          MIN)
        assert vi.labels == vc.labels == []

    def test_matching_errors_are_interpreter_identical(self, engine):
        """The compiled combine raises the interpreter's exact matching
        errors (dup keys can't be minted through the shared fixture's
        parser — every series has a distinct label set — so the two
        matchers are fed identical crafted vectors directly)."""
        from m3_tpu.query.engine import EvalError, Vector
        from m3_tpu.query.promql import BinaryExpr

        dup = Vector([{b"k": b"v"}, {b"k": b"v"}], np.ones((2, 3)))
        one = Vector([{b"k": b"v"}], np.ones((1, 3)))
        e = BinaryExpr("+", None, None, False, None)
        for lhs, rhs in ((dup, one), (one, dup)):
            with pytest.raises(EvalError) as interp:
                engine._vector_binary(e, lhs, rhs)
            with pytest.raises(EvalError) as comp:
                compiler._combine_vecbin(engine, "+", lhs, rhs)
            assert str(comp.value) == str(interp.value)

    def test_explain_reports_both_sides(self, engine, monkeypatch):
        monkeypatch.setenv("M3_TPU_QUERY_COMPILE", "1")
        q = "rate(reqs[5m]) + rate(reqs[5m])"
        engine.query_range(q, START, START + 10 * MIN, MIN)  # warm
        with explain.collect(analyze=True) as col:
            engine.query_range(q, START, START + 10 * MIN, MIN)
        doc = col.to_dict()
        assert doc["compiled"]["ran"] is True
        assert doc["compiled"]["binop"] == "+"
        sides = doc["compiled"]["sides"]
        assert len(sides) == 2 and all(s["ran"] for s in sides)
        # the plan tree shows the binary node with both subtrees
        [root] = doc["tree"]
        assert root["node"] == "binary"
        assert len(root["children"]) == 2


class TestMinMaxOverTime:
    """The sparse-table range-min stage (carried PR-10 follow-up):
    min/max_over_time plans stop falling back, with the host reduceat
    math as the exact parity reference (min/max are picks, so values are
    bit-identical, not just within the reassociation envelope)."""

    def test_sparse_table_parity(self, engine, monkeypatch):
        for q in ("max_over_time(reqs[5m])",
                  "min_over_time(reqs[2m]) * -1",
                  "quantile by (job) (0.5, max_over_time(reqs[6m]))"):
            before = dispatch.counters["query.compile[compiled]"]
            vi, vc = run_both(engine, monkeypatch, q, START,
                              START + 14 * MIN, MIN)
            assert dispatch.counters["query.compile[compiled]"] == \
                before + 1, f"plan not compiled: {q}"
            assert_parity(vi, vc, q)

    def test_scratch_cap_routes_base_to_host(self, engine, monkeypatch):
        """Past the table scratch cap the base matrix comes from the
        interpreter's exact host reduceat (shipped through the program's
        bmat input) — still ONE compiled program, never a fallback."""
        from m3_tpu.ops import temporal

        monkeypatch.setattr(temporal, "MINMAX_SCRATCH_ELEMS", 1)
        q = "sum by (host) (max_over_time(reqs[4m]))"
        before = dispatch.counters["query.compile[compiled]"]
        fb = dispatch.counters["query.compile[fallback]"]
        vi, vc = run_both(engine, monkeypatch, q, START, START + 12 * MIN,
                          MIN)
        assert dispatch.counters["query.compile[compiled]"] == before + 1
        assert dispatch.counters["query.compile[fallback]"] == fb
        assert_parity(vi, vc, q)


class TestFallbackAndPolicy:
    def test_uncovered_falls_back_counted(self, engine, monkeypatch):
        monkeypatch.setenv("M3_TPU_QUERY_COMPILE", "1")
        before = dispatch.counters["query.compile[fallback]"]
        v, _ = engine.query_range("topk(2, rate(reqs[5m]))", START,
                                  START + 10 * MIN, MIN)
        assert dispatch.counters["query.compile[fallback]"] == before + 1
        assert isinstance(v, Vector)  # interpreter served it, no error

    def test_disabled_engine_never_counts(self, engine, monkeypatch):
        monkeypatch.delenv("M3_TPU_QUERY_COMPILE", raising=False)
        before_c = dispatch.counters["query.compile[compiled]"]
        before_f = dispatch.counters["query.compile[fallback]"]
        engine.query_range("rate(reqs[5m])", START, START + 10 * MIN, MIN)
        assert dispatch.counters["query.compile[compiled]"] == before_c
        assert dispatch.counters["query.compile[fallback]"] == before_f

    def test_env_zero_overrides_configured_engine(self, db, monkeypatch):
        eng = Engine(db, resolve_tiers=False, query_compile=True)
        monkeypatch.setenv("M3_TPU_QUERY_COMPILE", "0")
        before = dispatch.counters["query.compile[compiled]"]
        eng.query_range("rate(reqs[5m])", START, START + 10 * MIN, MIN)
        assert dispatch.counters["query.compile[compiled]"] == before

    def test_host_policy_prefers_native_rate(self, monkeypatch):
        """Config-enabled (not forced) + CPU backend + native kernel
        present => extrapolated-rate plans go to the interpreter; forced
        env=1 compiles them; non-rate bases compile either way."""
        from m3_tpu.ops import native_hostops

        monkeypatch.setattr(native_hostops, "available", lambda: True)
        monkeypatch.setattr(dispatch, "_accelerator_present", lambda: False)
        monkeypatch.delenv("M3_TPU_NATIVE_OPS", raising=False)
        rate_spec = compiler.match(promql.parse("sum(rate(reqs[5m]))"))
        irate_spec = compiler.match(promql.parse("sum(irate(reqs[5m]))"))
        assert compiler._host_prefers_interpreter(rate_spec)
        assert not compiler._host_prefers_interpreter(irate_spec)
        # an accelerator flips the decision for rate too
        monkeypatch.setattr(dispatch, "_accelerator_present", lambda: True)
        assert not compiler._host_prefers_interpreter(rate_spec)


class TestPlanShapeCache:
    def test_repeated_identical_shape_compiles_once(self, engine,
                                                    monkeypatch):
        monkeypatch.setenv("M3_TPU_QUERY_COMPILE", "1")
        compiler._program.cache_clear()
        compiler.clear_plan_cache()
        q = "count by (job) (count_over_time(reqs[3m]))"
        miss0 = dispatch.counters["jit_query_plan[miss]"]
        hit0 = dispatch.counters["jit_query_plan[hit]"]
        for _ in range(4):
            engine.query_range(q, START, START + 12 * MIN, MIN)
        assert dispatch.counters["jit_query_plan[miss]"] == miss0 + 1
        assert dispatch.counters["jit_query_plan[hit]"] == hit0 + 3
        info = compiler.plan_cache_info()
        key = next(k for k in info if k.startswith("count_over_time|agg:count"))
        assert info[key] == {"hits": 3, "misses": 1}

    def test_plan_cache_is_bounded(self):
        compiler.clear_plan_cache()
        for i in range(compiler._PLAN_CACHE_CAP + 40):
            compiler._plan_cache_record(("sig", i, 1, 1), miss=True)
        assert len(compiler.plan_cache_info()) == compiler._PLAN_CACHE_CAP
        compiler.clear_plan_cache()

    def test_metric_shape_labels_bounded(self):
        """The shape= metric label set is capped (registry counters
        persist forever and signatures are user-controlled — the PR 7
        tenant-label cardinality class); the tail shares 'other'."""
        compiler.clear_plan_cache()
        labels = {compiler._shape_label(f"sig{i}|S1|T1|G1")
                  for i in range(compiler._SHAPE_LABEL_CAP + 20)}
        assert len(labels) == compiler._SHAPE_LABEL_CAP + 1
        assert "other" in labels
        # a capped shape keeps its own label on repeat queries
        assert compiler._shape_label("sig0|S1|T1|G1") == "sig0|S1|T1|G1"
        compiler.clear_plan_cache()

    def test_shape_buckets_reuse_the_program(self, engine, monkeypatch):
        """Different step counts inside one (S, T) bucket hit the same
        compiled executable — the recompile-bounding contract."""
        monkeypatch.setenv("M3_TPU_QUERY_COMPILE", "1")
        q = "sum by (host) (sum_over_time(reqs[2m]))"
        engine.query_range(q, START, START + 20 * MIN, MIN)  # warm bucket
        miss0 = dispatch.counters["jit_query_plan[miss]"]
        engine.query_range(q, START, START + 19 * MIN, MIN)  # same bucket
        assert dispatch.counters["jit_query_plan[miss]"] == miss0


class TestExplainSurface:
    def test_compiled_info_in_explain(self, engine, monkeypatch):
        monkeypatch.setenv("M3_TPU_QUERY_COMPILE", "1")
        q = "sum by (host) (sum_over_time(reqs[2m]))"
        engine.query_range(q, START, START + 10 * MIN, MIN)  # prime cache
        with explain.collect(analyze=True) as col:
            engine.query_range(q, START, START + 10 * MIN, MIN)
        doc = col.to_dict()
        assert doc["compiled"]["ran"] is True
        assert doc["compiled"]["cache"] == "hit"
        assert doc["compiled"]["cache_key"].startswith(
            "sum_over_time|agg:sum|S")
        # the plan tree still shows the resolved stages, selector innermost
        root = doc["tree"][0]
        assert root["node"] == "aggregate"
        assert root["children"][0]["node"] == "range_fn"
        assert root["children"][0]["children"][0]["node"] == "selector"

    def test_fallback_reason_in_explain(self, engine, monkeypatch):
        monkeypatch.setenv("M3_TPU_QUERY_COMPILE", "1")
        with explain.collect(analyze=True) as col:
            engine.query_range("topk(2, rate(reqs[5m]))", START,
                               START + 10 * MIN, MIN)
        doc = col.to_dict()
        assert doc["compiled"] == {"ran": False,
                                   "reason": "uncovered_plan_shape"}


class TestWindowBoundsBatch:
    def test_randomized_parity_with_loop(self):
        rng = np.random.default_rng(7)
        for _ in range(40):
            S = int(rng.integers(0, 30))
            per = []
            for _ in range(S):
                n = int(rng.integers(0, 25))
                t = np.sort(rng.integers(0, 10_000, n)).astype(np.int64)
                per.append((t, rng.normal(size=n)))
            raws = RaggedSeries.from_lists(per)
            T = int(rng.integers(0, 16))
            start = int(rng.integers(-2000, 2000))
            step = int(rng.integers(1, 400))
            eval_ts = (start + np.arange(T) * step).astype(np.int64)
            # half the trials take the aligned single-pass branch
            range_ns = step * int(rng.integers(0, 5)) if rng.random() < 0.5 \
                else int(rng.integers(0, 2500))
            lo1, hi1 = raws.window_bounds(eval_ts, range_ns)
            lo2, hi2 = raws.window_bounds_batch(eval_ts, range_ns)
            assert np.array_equal(lo1, lo2)
            assert np.array_equal(hi1, hi2)

    def test_non_ascending_grid_falls_back(self):
        raws = RaggedSeries.from_lists(
            [(np.array([5, 10], np.int64), np.array([1.0, 2.0]))])
        eval_ts = np.array([20, 10], np.int64)  # descending: loop path
        lo1, hi1 = raws.window_bounds(eval_ts, 4)
        lo2, hi2 = raws.window_bounds_batch(eval_ts, 4)
        assert np.array_equal(lo1, lo2) and np.array_equal(hi1, hi2)
