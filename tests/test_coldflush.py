"""Warm/cold write split + cold flush (VERDICT r2 "Next round" #7).

Reference semantics matched: writes to blocks that already flushed are a
separate WriteType routed to a separate flush pass producing version-
bumped volumes (src/dbnode/storage/series/buffer.go:77-147,
storage/coldflush.go, persist/fs/merger.go) — backfill must never drag
decode+merge work into the warm flush path.
"""

import numpy as np

from m3_tpu.storage.database import Database
from m3_tpu.storage.options import (
    DatabaseOptions,
    NamespaceOptions,
    RetentionOptions,
)
from m3_tpu.utils.ident import tags_to_id

HOUR = 3600 * 10**9
MIN = 60 * 10**9
START = 1_599_998_400_000_000_000  # aligned 2h block start


def bits(v: float) -> int:
    return int(np.float64(v).view(np.uint64))


def make_db(tmp_path):
    db = Database(str(tmp_path), DatabaseOptions(n_shards=2))
    db.create_namespace("default", NamespaceOptions(
        retention=RetentionOptions(
            retention_ns=48 * HOUR,
            block_size_ns=2 * HOUR,
            buffer_past_ns=10 * MIN,
        )
    ))
    db.open(START)
    return db


def write(db, name: bytes, t_ns: int, v: float):
    db.write_tagged("default", name, [(b"host", b"a")], t_ns, v)


def shard_of(db, name: bytes):
    ns = db.namespaces["default"]
    sid = tags_to_id(name, [(b"host", b"a")])
    return ns.shard_for(sid), sid


class TestWarmColdSplit:
    def test_backfill_classified_cold_and_kept_out_of_warm_pass(self, tmp_path):
        db = make_db(tmp_path)
        # warm ingest into block 0, then age it out and warm-flush it
        for i in range(20):
            write(db, b"cpu", START + i * MIN, float(i))
        now = START + 2 * HOUR + 11 * MIN  # past buffer_past
        assert db.tick(now)["flushed"] >= 1
        shard, sid = shard_of(db, b"cpu")
        assert shard._filesets[START].volume == 0
        warm_before = shard.warm_writes

        # backfill lands in the flushed block -> cold write
        write(db, b"cpu", START + 30 * MIN + 1 * MIN, 99.0)
        assert shard.cold_writes == 1
        assert shard.warm_writes == warm_before
        assert shard.cold_dirty_block_starts() == [START]
        # the warm pass must NOT pick the block up again
        assert shard.flushable_block_starts(now) == []
        assert db.namespaces["default"].flush(now) == 0
        assert shard._filesets[START].volume == 0  # untouched by warm pass

        # the cold pass merges it into a version-bumped volume
        assert db.namespaces["default"].cold_flush() == 1
        assert shard._filesets[START].volume == 1
        assert shard.cold_dirty_block_starts() == []

        # cold data queryable after its flush, merged with warm points
        t, v = shard.read(sid, START, START + 2 * HOUR)
        assert (START + 31 * MIN) in t.tolist()
        vals = v.view(np.float64)
        assert 99.0 in vals.tolist()
        db.close()

    def test_warm_flush_latency_structurally_flat_under_backfill(self, tmp_path):
        """The warm pass does no decode/merge work for backfilled blocks:
        with a cold-dirty block present, the warm pass flushes ONLY the
        new warm window (first volume), and the tick reports the cold
        merge separately."""
        db = make_db(tmp_path)
        for i in range(10):
            write(db, b"m", START + i * MIN, float(i))
        now1 = START + 2 * HOUR + 11 * MIN
        db.tick(now1)
        # sustained backfill into the flushed block + fresh warm ingest
        for i in range(50):
            write(db, b"m", START + 40 * MIN + i * MIN % (20 * MIN), float(i))
        for i in range(10):
            write(db, b"m", now1 + i * MIN, float(i))
        now2 = START + 4 * HOUR + 11 * MIN
        out = db.tick(now2)
        # warm pass: exactly the new window's first volume; cold pass
        # merged the backfill
        shard, sid = shard_of(db, b"m")
        assert out["cold_flushed"] >= 1
        assert shard._filesets[START].volume >= 1  # cold bump
        t, _ = shard.read(sid, START, START + 2 * HOUR)
        assert len(t) >= 20  # warm + backfill merged
        db.close()

    def test_cold_flush_survives_restart(self, tmp_path):
        """Version-bumped cold volumes are what bootstrap loads."""
        db = make_db(tmp_path)
        for i in range(5):
            write(db, b"r", START + i * MIN, float(i))
        db.tick(START + 2 * HOUR + 11 * MIN)
        write(db, b"r", START + 50 * MIN, 7.5)
        db.namespaces["default"].cold_flush()
        db.close()

        db2 = make_db(tmp_path)
        shard, sid = shard_of(db2, b"r")
        t, v = shard.read(sid, START, START + 2 * HOUR)
        assert (START + 50 * MIN) in t.tolist()
        assert 7.5 in v.view(np.float64).tolist()
        db2.close()
