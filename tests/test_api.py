"""End-to-end HTTP API tests: the minimum slice of SURVEY.md §7.4 —
write over HTTP -> storage -> PromQL query -> JSON, plus Prometheus
remote write/read wire compatibility (snappy + protobuf)."""

import json
import urllib.request

import numpy as np
import pytest

from m3_tpu.query.api import CoordinatorAPI
from m3_tpu.storage.database import Database
from m3_tpu.storage.options import DatabaseOptions
from m3_tpu.utils import protowire, snappy

MIN = 60 * 10**9
START = 1_599_998_400_000_000_000
START_S = START / 1e9


@pytest.fixture
def api(tmp_path):
    db = Database(str(tmp_path / "db"), DatabaseOptions(n_shards=4))
    db.create_namespace("default")
    db.open(START)
    a = CoordinatorAPI(db)
    port = a.serve(port=0)
    a.base = f"http://127.0.0.1:{port}"
    yield a
    a.shutdown()
    db.close()


def get(api, path):
    with urllib.request.urlopen(api.base + path) as r:
        return json.loads(r.read())


def post(api, path, body, ctype="application/octet-stream"):
    req = urllib.request.Request(
        api.base + path, data=body, headers={"Content-Type": ctype}, method="POST"
    )
    with urllib.request.urlopen(req) as r:
        data = r.read()
        return data if r.headers.get("Content-Type") == "application/x-protobuf" else json.loads(data)


class TestSnappy:
    def test_roundtrip(self, rng):
        for n in (0, 1, 59, 60, 61, 1000, 70000):
            data = rng.integers(0, 256, n).astype(np.uint8).tobytes()
            assert snappy.decompress(snappy.compress(data)) == data

    def test_decompress_with_copies(self):
        # hand-built stream: literal "abcd" + copy(offset=4, len=4)
        # tag1: literal len 4 -> ((4-1)<<2)|0 = 12; copy1: len=4 offset=4:
        # kind1: tag = ((4-4)&7)<<2 | 1 | (0<<5) = 1, offset byte = 4
        raw = bytes([8, 12]) + b"abcd" + bytes([1, 4])
        assert snappy.decompress(raw) == b"abcdabcd"


class TestProtowire:
    def test_write_request_roundtrip(self):
        series = [
            protowire.PromTimeSeries(
                labels=[(b"__name__", b"up"), (b"job", b"api")],
                samples=[(1600000000000, 1.0), (1600000015000, 0.0)],
            )
        ]
        enc = protowire.encode_write_request(series)
        dec = protowire.decode_write_request(enc)
        assert dec[0].labels == series[0].labels
        assert dec[0].samples == series[0].samples


class TestHTTP:
    def test_health(self, api):
        assert get(api, "/health")["ok"]

    def test_json_write_and_query(self, api):
        for i in range(5):
            post(api, "/api/v1/json/write", json.dumps({
                "metric": "cpu", "tags": {"host": "h1"},
                "timestamp": START_S + 60 * i, "value": float(i),
            }).encode(), "application/json")
        r = get(api, f"/api/v1/query_range?query=cpu&start={START_S}&end={START_S+240}&step=60")
        assert r["status"] == "success"
        res = r["data"]["result"]
        assert len(res) == 1
        assert res[0]["metric"]["host"] == "h1"
        assert [float(v) for _, v in res[0]["values"]] == [0.0, 1.0, 2.0, 3.0, 4.0]

    def test_remote_write_and_query(self, api):
        series = [
            protowire.PromTimeSeries(
                labels=[(b"__name__", b"reqs_total"), (b"code", b"200")],
                samples=[(int(START_S * 1000) + i * 15000, float(i * 30)) for i in range(20)],
            ),
            protowire.PromTimeSeries(
                labels=[(b"__name__", b"reqs_total"), (b"code", b"500")],
                samples=[(int(START_S * 1000) + i * 15000, float(i * 3)) for i in range(20)],
            ),
        ]
        body = snappy.compress(protowire.encode_write_request(series))
        r = post(api, "/api/v1/prom/remote/write", body)
        assert r["samples"] == 40
        t = START_S + 280
        r = get(api, f"/api/v1/query?query=sum(rate(reqs_total[2m]))&time={t}")
        v = float(r["data"]["result"][0]["value"][1])
        np.testing.assert_allclose(v, 2.0 + 0.2, rtol=1e-6)

    def test_remote_read(self, api):
        post(api, "/api/v1/json/write", json.dumps({
            "metric": "m", "tags": {"a": "b"}, "timestamp": START_S + 1, "value": 4.5,
        }).encode(), "application/json")
        q = protowire.PromReadQuery(
            start_ms=int(START_S * 1000), end_ms=int((START_S + 10) * 1000),
            matchers=[protowire.PromMatcher(0, b"__name__", b"m")],
        )
        body = bytearray()
        inner = (
            protowire.field_varint(1, q.start_ms)
            + protowire.field_varint(2, q.end_ms)
            + protowire.field_bytes(
                3,
                protowire.field_varint(1, 0)
                + protowire.field_bytes(2, b"__name__")
                + protowire.field_bytes(3, b"m"),
            )
        )
        body += protowire.field_bytes(1, inner)
        raw = post(api, "/api/v1/prom/remote/read", snappy.compress(bytes(body)))
        payload = snappy.decompress(raw)
        # parse QueryResult -> TimeSeries
        results = list(protowire.iter_fields(payload))
        assert len(results) == 1
        ts_list = protowire.decode_write_request(results[0][2])  # same shape
        assert ts_list[0].samples == [(int((START_S + 1) * 1000), 4.5)]
        assert (b"a", b"b") in ts_list[0].labels

    def test_labels_and_series(self, api):
        post(api, "/api/v1/json/write", json.dumps({
            "metric": "x", "tags": {"dc": "eu", "host": "h9"},
            "timestamp": START_S + 1, "value": 1.0,
        }).encode(), "application/json")
        r = get(api, "/api/v1/labels")
        assert set(r["data"]) >= {"__name__", "dc", "host"}
        r = get(api, "/api/v1/label/dc/values")
        assert r["data"] == ["eu"]
        r = get(api, '/api/v1/series?match[]=x{dc="eu"}'.replace("{", "%7B").replace("}", "%7D").replace('"', "%22"))
        assert r["data"][0]["host"] == "h9"

    def test_error_envelope(self, api):
        import urllib.error

        try:
            get(api, "/api/v1/query_range?query=sum(&start=0&end=1&step=1")
            raise AssertionError("expected 400")
        except urllib.error.HTTPError as e:
            body = json.loads(e.read())
            assert body["status"] == "error"
            assert e.code == 400

    def test_instant_query_vector(self, api):
        post(api, "/api/v1/json/write", json.dumps({
            "metric": "g", "tags": {}, "timestamp": START_S + 5, "value": 2.5,
        }).encode(), "application/json")
        r = get(api, f"/api/v1/query?query=g*2&time={START_S+10}")
        assert r["data"]["resultType"] == "vector"
        assert float(r["data"]["result"][0]["value"][1]) == 5.0


class TestInfluxWrite:
    def test_line_protocol_ingest(self, api):
        from m3_tpu.index.query import Matcher, MatchType

        t0 = int(START_S) + 1
        lines = (
            b"cpu,host=h1,dc=east usage=0.5,idle=99i %d000000000\n" % t0
            + b"mem,host=h1 value=2048 %d000000000\n" % (t0 + 1)
            + b"weird\\ name,k=a\\,b value=7 %d000000000\n" % (t0 + 2)
        )
        req = urllib.request.Request(
            api.base + "/api/v1/influxdb/write", data=lines, method="POST")
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 204
        db = api.db
        lo, hi = START, START + 60 * 10**9
        res = db.query("default",
                       [Matcher(MatchType.EQUAL, b"__name__", b"cpu_usage")],
                       lo, hi)
        assert len(res) == 1 and res[0][2][0].value == 0.5
        assert dict(res[0][1])[b"host"] == b"h1"
        res = db.query("default",
                       [Matcher(MatchType.EQUAL, b"__name__", b"mem")], lo, hi)
        assert res[0][2][0].value == 2048.0  # 'value' field keeps bare name
        res = db.query("default",
                       [Matcher(MatchType.EQUAL, b"__name__", b"weird name")],
                       lo, hi)
        assert dict(res[0][1])[b"k"] == b"a,b"

    def test_precision_and_errors(self, api):
        import urllib.error

        from m3_tpu.index.query import Matcher, MatchType

        t0 = int(START_S) + 5
        req = urllib.request.Request(
            api.base + "/api/v1/influxdb/write?precision=s",
            data=b"secs value=1 %d" % t0, method="POST")
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 204
        res = api.db.query(
            "default", [Matcher(MatchType.EQUAL, b"__name__", b"secs")],
            START, START + 60 * 10**9)
        assert res[0][2][0].timestamp_ns == t0 * 10**9
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(
                api.base + "/api/v1/influxdb/write",
                data=b"garbage with no fields", method="POST"), timeout=10)
        assert ei.value.code == 400

    def test_partial_write_reports_error(self, api):
        import urllib.error

        from m3_tpu.index.query import Matcher, MatchType

        t0 = int(START_S) + 8
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(
                api.base + "/api/v1/influxdb/write",
                data=b"cpu good=1,bad=abc %d000000000" % t0, method="POST"),
                timeout=10)
        assert ei.value.code == 400
        assert b"partial write" in ei.value.read()
        # the parseable field WAS written despite the bad sibling
        res = api.db.query(
            "default", [Matcher(MatchType.EQUAL, b"__name__", b"cpu_good")],
            START, START + 60 * 10**9)
        assert res[0][2][0].value == 1.0
