"""Cluster layer tests: KV, elections, placements, topology, and the
multi-node quorum harness.

Mirrors the reference's in-process integration style (SURVEY.md §4.4):
several real Database nodes in one process under a fake-etcd placement,
quorum writes/reads, node-down behavior, and elastic add-node bootstrap.
"""

import json

import pytest

from m3_tpu.client.session import ConsistencyError, Session
from m3_tpu.cluster import placement as pl
from m3_tpu.cluster.kv import FileKVStore, KeyNotFound, KVStore, VersionMismatch
from m3_tpu.cluster.placement import Instance, ShardState
from m3_tpu.cluster.services import LeaderService, Services
from m3_tpu.cluster.topology import ConsistencyLevel, TopologyMap, majority
from m3_tpu.storage.database import Database
from m3_tpu.storage.options import DatabaseOptions

HOUR = 3600 * 10**9
START = 1_599_998_400_000_000_000
SEC = 10**9


class TestKV:
    def test_versioned_set_get(self):
        kv = KVStore()
        assert kv.set("a", b"1") == 1
        assert kv.set("a", b"2") == 2
        assert kv.get("a").data == b"2"
        with pytest.raises(KeyNotFound):
            kv.get("missing")

    def test_cas(self):
        kv = KVStore()
        kv.set("k", b"v1")
        with pytest.raises(VersionMismatch):
            kv.check_and_set("k", 99, b"nope")
        assert kv.check_and_set("k", 1, b"v2") == 2

    def test_watch(self):
        kv = KVStore()
        kv.set("w", b"initial")
        seen = []
        kv.watch("w", lambda k, v: seen.append(v.data if v else None))
        assert seen == [b"initial"]  # current value delivered immediately
        kv.set("w", b"updated")
        kv.delete("w")
        assert seen == [b"initial", b"updated", None]

    def test_file_backed_persistence(self, tmp_path):
        p = str(tmp_path / "kv.json")
        kv = FileKVStore(p)
        kv.set("x", b"hello")
        kv.set("y", bytes(range(256)))
        kv2 = FileKVStore(p)
        assert kv2.get("x").data == b"hello"
        assert kv2.get("y").version == 1
        assert kv2.get("y").data == bytes(range(256))


class TestLeaderElection:
    def test_campaign_and_failover(self):
        kv = KVStore()
        t0 = 1_000_000_000_000
        a = LeaderService(kv, "flush", "node-a", lease_ttl_s=10)
        b = LeaderService(kv, "flush", "node-b", lease_ttl_s=10)
        assert a.campaign(t0)
        assert not b.campaign(t0 + int(1e9))
        assert a.leader(t0 + int(1e9)) == "node-a"
        assert b.leader(t0 + int(1e9)) == "node-a"
        # leader renews within ttl
        assert a.campaign(t0 + int(5e9))
        # leader dies: after ttl, b seizes
        t_late = t0 + int(20e9)
        assert b.campaign(t_late)
        assert b.is_leader(t_late)

    def test_resign(self):
        kv = KVStore()
        a = LeaderService(kv, "e", "a")
        b = LeaderService(kv, "e", "b")
        assert a.campaign(10**15)
        a.resign()
        assert b.campaign(10**15)

    def test_services_heartbeat(self):
        kv = KVStore()
        s = Services(kv, heartbeat_ttl_s=10)
        t0 = 10**15
        import m3_tpu.cluster.services as svc_mod

        # advertise uses wall time; emulate by writing directly
        kv.set("_sd/db/n1", json.dumps(
            {"service": "db", "instance_id": "n1", "endpoint": "e1",
             "heartbeat_ns": t0}).encode())
        kv.set("_sd/db/n2", json.dumps(
            {"service": "db", "instance_id": "n2", "endpoint": "e2",
             "heartbeat_ns": t0 - int(60e9)}).encode())
        live = s.instances("db", now_ns=t0 + int(1e9))
        assert [a.instance_id for a in live] == ["n1"]


class TestPlacement:
    def test_initial_rf3(self):
        insts = [Instance(f"n{i}", isolation_group=f"rack{i % 3}") for i in range(6)]
        p = pl.initial_placement(insts, n_shards=12, replica_factor=3)
        p.validate()
        # every shard has 3 AVAILABLE owners in 3 distinct racks
        for sid in range(12):
            owners = p.instances_for_shard(sid)
            assert len(owners) == 3
            assert len({o.isolation_group for o in owners}) == 3
        # balanced: 12*3/6 = 6 shards per instance
        assert all(len(i.shards) == 6 for i in p.instances.values())

    def test_add_instance_minimal_churn(self):
        insts = [Instance(f"n{i}") for i in range(3)]
        p = pl.initial_placement(insts, n_shards=9, replica_factor=3)
        p2 = pl.add_instance(p, Instance("n3"))
        p2.validate()  # LEAVING donors still count until handoff completes
        new = p2.instances["n3"]
        init_ids = new.shard_ids(ShardState.INITIALIZING)
        assert 0 < len(init_ids) <= 9 * 3 // 4 + 1
        # donors keep serving while the new node bootstraps
        for sid in init_ids:
            donor_id = new.shards[sid].source_id
            assert p2.instances[donor_id].shards[sid].state == ShardState.LEAVING
        # complete bootstrap
        p3 = pl.mark_available(p2, "n3")
        p3.validate()
        for sid in init_ids:
            assert p3.instances["n3"].shards[sid].state == ShardState.AVAILABLE

    def test_remove_instance(self):
        insts = [Instance(f"n{i}") for i in range(4)]
        p = pl.initial_placement(insts, n_shards=8, replica_factor=3)
        p2 = pl.remove_instance(p, "n0")
        p2.validate()
        # every ex-n0 shard has a new INITIALIZING owner elsewhere
        for sid in p.instances["n0"].shards:
            owners = {i.id for i in p2.instances_for_shard(sid)}
            assert "n0" not in owners
            assert len(owners) == 3

    def test_replace_instance(self):
        insts = [Instance(f"n{i}") for i in range(3)]
        p = pl.initial_placement(insts, n_shards=6, replica_factor=3)
        p2 = pl.replace_instance(p, "n1", Instance("n9"))
        assert set(p2.instances["n9"].shards) == set(p.instances["n1"].shards)
        p3 = pl.mark_available(p2, "n9")
        p3.validate()
        assert "n1" not in p3.instances

    def test_mirrored_pairs(self):
        pairs = [(Instance("l1"), Instance("f1")), (Instance("l2"), Instance("f2"))]
        p = pl.mirrored_placement(pairs, n_shards=8)
        p.validate()
        assert p.is_mirrored
        assert set(p.instances["l1"].shards) == set(p.instances["f1"].shards)
        assert p.instances["l1"].shard_set_id == p.instances["f1"].shard_set_id

    def test_json_roundtrip(self):
        insts = [Instance(f"n{i}") for i in range(3)]
        p = pl.initial_placement(insts, n_shards=4, replica_factor=2)
        p2 = pl.Placement.from_json(p.to_json())
        assert p2.n_shards == 4 and p2.replica_factor == 2
        assert {i.id for i in p2.instances.values()} == {"n0", "n1", "n2"}

    # -- elasticity edge cases (PR 17): mutations composed mid-handoff --

    def test_remove_donor_while_handoff_pending(self):
        """remove_instance on a node that is DONOR for an unfinished add:
        the mid-flight INITIALIZING owner IS the shard's replacement, so
        the drain must not assign a redundant third owner."""
        insts = [Instance(f"n{i}", isolation_group=f"g{i}") for i in range(3)]
        p = pl.initial_placement(insts, n_shards=6, replica_factor=2)
        p2 = pl.add_instance(p, Instance("n3", isolation_group="g3"))
        pending = p2.instances["n3"].shard_ids(ShardState.INITIALIZING)
        assert pending  # the prior handoff is genuinely mid-flight
        victim = p2.instances["n3"].shards[pending[0]].source_id
        p3 = pl.remove_instance(p2, victim)
        p3.validate()  # no shard gained more than RF non-LEAVING owners
        # n3's pending handoffs survive the donor's drain intact
        for sid in pending:
            sh = p3.instances["n3"].shards.get(sid)
            assert sh is not None and sh.state == ShardState.INITIALIZING
        # every in-flight owner completes; the drained donor is pruned
        cur = p3
        for iid in sorted(p3.instances):
            if iid in cur.instances:
                cur = pl.mark_available(cur, iid)
        cur.validate()
        assert victim not in cur.instances
        assert all(sh.state == ShardState.AVAILABLE
                   for inst in cur.instances.values()
                   for sh in inst.shards.values())

    def test_replace_donor_mid_stream(self):
        """replace_instance of a donor mid-stream: the replacement
        inherits only the shards the donor was SERVING — a shard already
        streaming to its new owner keeps that single replacement (and its
        original source_id), instead of growing a second copy."""
        insts = [Instance(f"n{i}", isolation_group=f"g{i}") for i in range(3)]
        p = pl.initial_placement(insts, n_shards=6, replica_factor=2)
        p2 = pl.add_instance(p, Instance("n3", isolation_group="g3"))
        pending = p2.instances["n3"].shard_ids(ShardState.INITIALIZING)
        donor_id = p2.instances["n3"].shards[pending[0]].source_id
        p3 = pl.replace_instance(p2, donor_id,
                                 Instance("n9", isolation_group="g9"))
        p3.validate()
        mid_stream = [sid for sid in pending
                      if p2.instances["n3"].shards[sid].source_id == donor_id]
        for sid in mid_stream:
            assert sid not in p3.instances["n9"].shards
            # the in-flight move still names its original source; cutover
            # reaps the old instance's LEAVING copy through it
            assert p3.instances["n3"].shards[sid].source_id == donor_id
        for sh in p3.instances["n9"].shards.values():
            assert sh.state == ShardState.INITIALIZING
            assert sh.source_id == donor_id
        cur = p3
        for iid in sorted(p3.instances):
            if iid in cur.instances:
                cur = pl.mark_available(cur, iid)
        cur.validate()
        assert donor_id not in cur.instances

    def test_mark_available_stale_or_removed_source(self):
        """Cutover with a stale source: a source that was pruned (donor
        crashed mid-drain) or whose copy is no longer LEAVING must be
        tolerated — a KeyError here would poison the CAS retry loop."""
        p = pl.Placement(n_shards=2, replica_factor=1)
        x = Instance("x")
        x.shards[0] = pl.Shard(0, ShardState.INITIALIZING, "ghost")
        x.shards[1] = pl.Shard(1, ShardState.AVAILABLE)
        p.instances["x"] = x
        out = pl.mark_available(p, "x")
        assert out.instances["x"].shards[0].state == ShardState.AVAILABLE

        # source exists but no longer holds the shard LEAVING (cancelled
        # drain): flip the target, leave the source's copy alone
        p2 = pl.Placement(n_shards=1, replica_factor=2)
        a, b = Instance("a"), Instance("b")
        a.shards[0] = pl.Shard(0, ShardState.AVAILABLE)
        b.shards[0] = pl.Shard(0, ShardState.INITIALIZING, "a")
        p2.instances = {"a": a, "b": b}
        out2 = pl.mark_available(p2, "b")
        assert out2.instances["b"].shards[0].state == ShardState.AVAILABLE
        assert out2.instances["a"].shards[0].state == ShardState.AVAILABLE

    def test_json_roundtrip_mixed_states_and_sources(self):
        """Serialization through KV mid-elasticity: INITIALIZING (with
        source), LEAVING, and AVAILABLE shards all survive a round-trip
        byte-exactly — the handoff controllers on every node decide from
        this document."""
        insts = [Instance(f"n{i}", isolation_group=f"g{i}") for i in range(3)]
        p = pl.initial_placement(insts, n_shards=6, replica_factor=2)
        p2 = pl.add_instance(p, Instance("n3", isolation_group="g3"))
        p2.instances["n3"].endpoint = "http://127.0.0.1:9003"
        rt = pl.Placement.from_json(p2.to_json())
        assert rt.n_shards == p2.n_shards
        assert rt.replica_factor == p2.replica_factor
        states = {s.value for inst in rt.instances.values()
                  for s in (sh.state for sh in inst.shards.values())}
        assert {"INITIALIZING", "LEAVING", "AVAILABLE"} <= states
        for iid, inst in p2.instances.items():
            got = rt.instances[iid]
            assert got.endpoint == inst.endpoint
            assert ({(s.id, s.state, s.source_id)
                     for s in inst.shards.values()}
                    == {(s.id, s.state, s.source_id)
                        for s in got.shards.values()})
        rt.validate()


def make_cluster(tmp_path, n_nodes=3, n_shards=6, rf=3):
    insts = [Instance(f"node-{i}") for i in range(n_nodes)]
    p = pl.initial_placement(insts, n_shards=n_shards, replica_factor=rf)
    nodes = {}
    for inst in insts:
        db = Database(str(tmp_path / inst.id), DatabaseOptions(n_shards=n_shards))
        db.create_namespace("default")
        db.open(START)
        nodes[inst.id] = db
    topo = TopologyMap(p)
    return p, topo, nodes


class TestQuorumSession:
    def test_write_read_quorum(self, tmp_path):
        p, topo, nodes = make_cluster(tmp_path)
        sess = Session(topo, nodes,
                       write_consistency=ConsistencyLevel.MAJORITY,
                       read_consistency=ConsistencyLevel.ONE)
        res = sess.write_tagged("default", b"cpu", [(b"h", b"1")], START + SEC, 1.5)
        assert res.acks == 3  # all replicas took the write
        from m3_tpu.utils.ident import tags_to_id

        sid = tags_to_id(b"cpu", [(b"h", b"1")])
        dps = sess.fetch("default", sid, START, START + HOUR)
        assert dps == [(START + SEC, 1.5)]
        for db in nodes.values():
            db.close()

    def test_one_node_down_majority_still_writes(self, tmp_path):
        p, topo, nodes = make_cluster(tmp_path)
        dead = sorted(nodes)[0]
        nodes[dead].close()

        class Down:
            def write_tagged(self, *a, **k):
                raise ConnectionError("node down")

            def read(self, *a, **k):
                raise ConnectionError("node down")

        live = dict(nodes)
        live[dead] = Down()
        sess = Session(topo, live, write_consistency=ConsistencyLevel.MAJORITY)
        res = sess.write_tagged("default", b"m", [], START + SEC, 2.0)
        assert res.acks == 2 and len(res.errors) == 1
        # ALL consistency fails with a node down
        sess_all = Session(topo, live, write_consistency=ConsistencyLevel.ALL)
        with pytest.raises(ConsistencyError):
            sess_all.write_tagged("default", b"m2", [], START + SEC, 1.0)
        for k, db in nodes.items():
            if k != dead:
                db.close()

    def test_majority_fails_with_two_down(self, tmp_path):
        p, topo, nodes = make_cluster(tmp_path)
        ids = sorted(nodes)

        class Down:
            def write_tagged(self, *a, **k):
                raise ConnectionError("down")

            def read(self, *a, **k):
                raise ConnectionError("down")

        live = dict(nodes)
        live[ids[0]] = Down()
        live[ids[1]] = Down()
        sess = Session(topo, live, write_consistency=ConsistencyLevel.MAJORITY)
        with pytest.raises(ConsistencyError):
            sess.write_tagged("default", b"m", [], START + SEC, 1.0)
        for db in nodes.values():
            db.close()

    def test_replica_merge_prefers_latest(self, tmp_path):
        # one replica missing a point: merged read still returns it
        p, topo, nodes = make_cluster(tmp_path)
        sess = Session(topo, nodes)
        sess.write_tagged("default", b"m", [], START + SEC, 1.0)
        from m3_tpu.utils.ident import tags_to_id

        sid = tags_to_id(b"m", [])
        # write an extra point directly to ONE replica only
        shard = sess._shard(sid)
        host = topo.readable_hosts_for_shard(shard)[0]
        nodes[host].write_tagged("default", b"m", [], START + 2 * SEC, 9.0)
        sess_all = Session(topo, nodes, read_consistency=ConsistencyLevel.ALL)
        dps = sess_all.fetch("default", sid, START, START + HOUR)
        assert dps == [(START + SEC, 1.0), (START + 2 * SEC, 9.0)]
        for db in nodes.values():
            db.close()

    def test_majority_value(self):
        assert majority(3) == 2
        assert majority(5) == 3
        assert majority(1) == 1
